"""Static-graph meta-optimizers: strategy-driven Program rewrites.

Parity: python/paddle/distributed/fleet/meta_optimizers/{amp_optimizer,
recompute_optimizer,gradient_merge_optimizer,sharding_optimizer,
lamb_optimizer,lars_optimizer}.py — the reference's static pass zoo rewrites
ProgramDesc op-by-op (insert cast ops, checkpoint subgraphs, merge loops).

TPU-first: our captured Program is a list of jnp-closure op records, so each
"pass" is a rewrite at that level instead of protobuf surgery:

- AMP       → cast captured parameters to the AMP dtype wholesale (the pure
              bf16/fp16 recipe — on TPU bf16 is the MXU-native dtype, so the
              reference's per-op white/black-list cast insertion degenerates
              to "run the graph low-precision, keep fp32 masters"), seed fp32
              master weights from the ORIGINAL fp32 values, and loss-scale
              through amp.GradScaler for fp16.
- Recompute → group the op list into segments bounded by user checkpoints;
              each segment replays as ONE tape node through fleet's
              ``recompute`` (forward under no_grad, re-run in backward), so
              live activations scale with segment boundaries, not ops.
- GradientMerge → k-step micro-batch accumulation around the registered
              minimize hook (grads accumulate across Executor.run calls;
              the update fires every k-th run).
- Sharding  → wrap the inner optimizer in DygraphShardingOptimizer (the same
              PartitionSpec placement machinery the dygraph path proves).
- Lamb/Lars → swap the update rule, preserving lr/params/decay.

`fleet.distributed_optimizer(opt, strategy)` returns StaticMetaOptimizer in
static mode; its `minimize(loss)` applies the stack then registers itself so
Executor.run drives `_static_apply` each iteration.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Lamb, Momentum, Optimizer


class LarsMomentum(Momentum):
    """LARS: layerwise trust-ratio-scaled momentum update.

    Parity: LarsMomentumOptimizer (lars_momentum_op) — local_lr =
    lr · coeff · ||w|| / (||g|| + λ·||w||), then the momentum rule.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, multi_precision=False):
        super().__init__(learning_rate, momentum, parameters,
                         grad_clip=grad_clip, multi_precision=multi_precision)
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)

    def _update_param(self, p, g, lr):
        m = self._master(p)
        w32 = m._data.astype(jnp.float32)
        graw = g._data.astype(jnp.float32)
        g32 = graw + self._lars_wd * w32
        wn = jnp.linalg.norm(w32)
        gn = jnp.linalg.norm(graw)
        # denominator is ||g|| + λ·||w|| (NOT ||g + λw||, which can cancel
        # to ~0 near convergence and blow the ratio up unboundedly)
        trust = jnp.where(
            (wn > 0) & (gn > 0),
            self._lars_coeff * wn / (gn + self._lars_wd * wn + 1e-12), 1.0)
        vel = self._acc("velocity", p)
        v_new = self._momentum * vel._data + lr * trust * g32
        vel._data = v_new
        self._apply(p, w32 - v_new)


def _swap_update_rule(inner: Optimizer, strategy):
    """lamb/lars passes: replace the update rule, keep lr + param list.
    Parity: LambOptimizer/LarsOptimizer _can_apply → minimize-with-swap."""
    if strategy.lamb:
        cfg = getattr(strategy, "lamb_configs", {}) or {}
        return Lamb(learning_rate=inner._lr,
                    lamb_weight_decay=float(cfg.get("lamb_weight_decay", 0.01)),
                    parameters=inner._parameter_list,
                    grad_clip=inner._grad_clip,
                    multi_precision=inner._multi_precision)
    if strategy.lars:
        cfg = getattr(strategy, "lars_configs", {}) or {}
        return LarsMomentum(
            learning_rate=inner._lr,
            momentum=float(cfg.get("momentum", 0.9)),
            lars_coeff=float(cfg.get("lars_coeff", 0.001)),
            lars_weight_decay=float(cfg.get("lars_weight_decay", 0.0005)),
            parameters=inner._parameter_list,
            grad_clip=inner._grad_clip,
            multi_precision=inner._multi_precision)
    return inner


def _apply_amp_pass(program, optimizer, amp_configs):
    """Pure-low-precision AMP over a captured Program.

    Seeds fp32 masters from the pre-cast values (the reference's
    cast_model_to_fp16 + master-grad path keeps the fp32 originals too),
    then casts every captured float32 parameter down. Returns a GradScaler
    for fp16 (bf16 needs none — its exponent range matches fp32).
    """
    dtype = jnp.float16 if (
        amp_configs.get("dtype") in ("float16", "fp16")
        or amp_configs.get("use_pure_fp16")) else jnp.bfloat16
    optimizer._multi_precision = True
    # scope: the optimizer's own params (a user list, or the global set for
    # list-less optimizers) — NOT program.all_parameters(), which reads the
    # process-global registry and would downcast co-resident models
    for p in optimizer._params():
        if p.dtype != jnp.float32:
            continue
        optimizer._seed_master(p, p._data)
        p._data = p._data.astype(dtype)
    if dtype == jnp.float16 and amp_configs.get(
            "use_dynamic_loss_scaling", True):
        from ....amp.grad_scaler import GradScaler
        return GradScaler(
            init_loss_scaling=float(
                amp_configs.get("init_loss_scaling", 2.0 ** 15)),
            incr_every_n_steps=int(
                amp_configs.get("incr_every_n_steps", 1000)),
            decr_every_n_nan_or_inf=int(
                amp_configs.get("decr_every_n_nan_or_inf", 2)))
    return None


def _apply_recompute_pass(program, checkpoints, loss):
    """Rewrite program.ops into recompute segments bounded by checkpoints.

    checkpoints: Tensors (or their .name strings) marking the activations to
    KEEP; everything between two checkpoints is re-run during backward.
    Constraint (same as the reference's recompute pass): fetches must be
    boundary vars — intermediates inside a segment are freed.
    """
    from ....static import _OpRecord, _RecomputeSegment

    ck_uids = set()
    by_name = {}
    for op in program.ops:
        for t in op.inputs:
            if getattr(t, "name", None):
                by_name[t.name] = t._uid
    for c in checkpoints:
        if isinstance(c, str):
            if c in by_name:
                ck_uids.add(by_name[c])
            else:
                # a typo'd/unnamed checkpoint must not silently disable
                # segmentation — the user believes memory is bounded
                raise ValueError(
                    f"recompute checkpoint {c!r} does not name any "
                    f"recorded tensor; known names: {sorted(by_name)[:20]}")
        else:
            ck_uids.add(c._uid)
    loss_uid = loss._uid

    # uid -> index of the last op (or hook) consuming it, for output pruning
    last_use: dict[int, int] = {}
    for i, op in enumerate(program.ops):
        for t in op.inputs:
            last_use[t._uid] = i

    new_ops: list = []
    cur: list[_OpRecord] = []

    def _close(end_idx):
        if not cur:
            return
        if len(cur) == 1:
            new_ops.append(cur[0])
            cur.clear()
            return
        produced = set()
        for op in cur:
            produced.update(op.output_ids)
        ins, seen = [], set()
        for op in cur:
            for t in op.inputs:
                if t._uid not in produced and t._uid not in seen:
                    seen.add(t._uid)
                    ins.append(t)
        outs = [u for u in dict.fromkeys(
            u for op in cur for u in op.output_ids)
            if u == loss_uid or u in ck_uids
            or last_use.get(u, -1) > end_idx]
        if not outs:  # dead tail segment (e.g. metrics after loss): keep raw
            new_ops.extend(cur)
        else:
            new_ops.append(_RecomputeSegment(cur[:], ins, outs))
        cur.clear()

    for i, op in enumerate(program.ops):
        cur.append(op)
        if any(u in ck_uids or u == loss_uid for u in op.output_ids):
            _close(i)
    _close(len(program.ops) - 1)
    program.ops = new_ops


class StaticMetaOptimizer:
    """fleet.distributed_optimizer(...) in static mode.

    Applies the strategy's pass stack at minimize() time, then registers
    itself as the program's minimize hook; Executor.run calls
    `_static_apply(loss)` once per iteration.
    """

    def __init__(self, optimizer, strategy, hcg=None):
        self._user_opt = optimizer
        self._strategy = strategy
        self._hcg = hcg
        self._opt = optimizer
        self._scaler = None
        self._k_steps = 1
        self._merge_avg = True
        self._accum = 0

    def __getattr__(self, name):
        return getattr(self._opt, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ....static import default_main_program
        program = default_main_program()
        s = self._strategy
        opt = _swap_update_rule(self._user_opt, s)
        if s.recompute:
            ckpts = s.recompute_configs.get("checkpoints", []) or []
            if ckpts:
                _apply_recompute_pass(program, ckpts, loss)
        if s.amp:
            self._scaler = _apply_amp_pass(program, opt, s.amp_configs)
        if s.sharding and s.sharding_configs.get("sharding_degree", 1) > 1:
            from ..meta_parallel.sharding.group_sharded import (
                DygraphShardingOptimizer)
            opt = DygraphShardingOptimizer(opt, self._hcg)
        if s.gradient_merge:
            self._k_steps = max(1, int(
                s.gradient_merge_configs.get("k_steps", 1)))
            self._merge_avg = bool(s.gradient_merge_configs.get("avg", True))
        if s.dgc or s.localsgd:
            raise NotImplementedError(
                "strategy.dgc/localsgd: gradient compression and periodic "
                "averaging are GPU-interconnect optimizations; on TPU the "
                "ICI-scheduled XLA collectives they work around do not "
                "exist. Unset the flag.")
        self._opt = opt
        program._add_minimize(self, loss)
        return None, None

    # Executor entry point (one training iteration's backward+update)
    def _static_apply(self, loss):
        if self._scaler is not None:
            loss = self._scaler.scale(loss)
        loss.backward()
        self._accum += 1
        if self._accum % self._k_steps:
            return  # merge phase: keep accumulating, no update
        if self._k_steps > 1 and self._merge_avg:
            inv = 1.0 / self._k_steps
            for p in self._opt._params():
                if p.grad is not None:
                    p.grad._data = p.grad._data * inv
        if self._scaler is not None:
            self._scaler.step(self._opt)
            self._scaler.update()
        else:
            self._opt.step()
        self._opt.clear_grad()
