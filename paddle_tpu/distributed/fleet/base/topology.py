"""Hybrid-parallel topology over a named jax Mesh.

Parity: python/paddle/distributed/fleet/base/topology.py ::
CommunicateTopology / HybridCommunicateGroup — rank ↔ (dp, pp, sharding, sep,
mp) coordinate mapping and per-axis communicator groups.

TPU-native: the topology IS a jax.sharding.Mesh with axes
('dp','pp','sharding','sep','mp'); each axis group is a ProcessGroupXLA bound
to that axis name, so collectives lower to XLA ops over ICI (fast, within
slice) for the inner axes and DCN for the outer ones — axis order places mp
innermost (most bandwidth-hungry) exactly as the reference packs mp into
NVLink domains.
"""
from __future__ import annotations

import itertools
from functools import reduce

import jax
import numpy as np
from jax.sharding import Mesh

from ...communication.group import Group, ProcessGroupXLA

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "_HYBRID_GROUP"]

_HYBRID_GROUP = [None]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = itertools.product(*map(range, self._dims))
        self._world_size = int(np.prod(self._dims))
        ranks = np.arange(self._world_size).reshape(self._dims)
        self._rank_map = ranks
        self._coord_of = {int(r): tuple(c) for c, r in np.ndenumerate(ranks)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._rank_map[coord])

    def get_coord(self, rank):
        return self._coord_of[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis == index."""
        ax = self._parallel_names.index(axis_name)
        sel = [slice(None)] * len(self._dims)
        sel[ax] = index
        return sorted(int(r) for r in self._rank_map[tuple(sel)].reshape(-1))

    def get_comm_list(self, axis_name):
        """List of rank-groups along axis (vary axis, fix others)."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_map, ax, -1)
        return [sorted(int(r) for r in row)
                for row in moved.reshape(-1, self._dims[ax])]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return int(self._rank_map[tuple(coord)])


# paddle axis name → mesh axis name
_AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding",
             "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        # single-controller: the "global rank" used for group construction is
        # process-level; per-chip coordinates live inside compiled programs.
        self.global_rank = min(jax.process_index(), self.nranks - 1)
        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = (topology.get_dim("sep")
                            if "sep" in topology.get_hybrid_group_names() else 1)

        self._mesh = self._build_mesh()
        self._groups: dict[str, Group] = {}
        for paddle_axis, mesh_axis in _AXIS_MAP.items():
            if paddle_axis not in topology.get_hybrid_group_names():
                continue
            self._groups[paddle_axis] = self._make_axis_group(paddle_axis,
                                                              mesh_axis)
        _HYBRID_GROUP[0] = self

    # ------------------------------------------------------------------ mesh
    def _build_mesh(self) -> Mesh:
        dims = {"dp": self._dp_degree, "pp": self._pp_degree,
                "sharding": self._sharding_degree, "sep": self._sep_degree,
                "mp": self._mp_degree}
        devs = np.asarray(jax.devices())
        need = int(np.prod(list(dims.values())))
        axes = ("pp", "dp", "sharding", "sep", "mp")
        shape = (dims["pp"], dims["dp"], dims["sharding"], dims["sep"],
                 dims["mp"])
        if devs.size >= need:
            # multi-slice pods: per-chip ICI only spans a slice; traffic
            # between slices rides DCN. Put the DATA axis across slices
            # (dp's gradient allreduce is the least latency-sensitive,
            # once-per-step collective — the reference runs its NCCL dp
            # ring over the inter-node network for the same reason) and
            # keep sharding/sep/mp inside each slice's ICI.
            # create_hybrid_device_mesh needs real slice topology info —
            # absent (CPU, single slice), fall through to the flat mesh.
            try:
                slices = {getattr(d, "slice_index", 0)
                          for d in devs[:need].tolist()}
                n_slices = len(slices)
                if n_slices > 1 and dims["dp"] % n_slices == 0:
                    from jax.experimental import mesh_utils
                    # signature: (mesh_shape, dcn_mesh_shape, devices=...)
                    # — mesh_shape is the per-slice (ICI) factorization
                    hyb = mesh_utils.create_hybrid_device_mesh(
                        (dims["pp"], dims["dp"] // n_slices,
                         dims["sharding"], dims["sep"], dims["mp"]),
                        (1, n_slices, 1, 1, 1),
                        devices=devs[:need].tolist())
                    return Mesh(hyb, axes)
            except Exception as e:
                # flat reshape below is always correct, just not
                # DCN-placement-optimal — but NEVER silently: a failure
                # here on a real pod means dp gradient traffic may cross
                # DCN unplanned
                import warnings
                warnings.warn(
                    f"hybrid (ICI/DCN) mesh construction failed, using "
                    f"flat device order: {type(e).__name__}: {e}",
                    RuntimeWarning, stacklevel=2)
        if devs.size < need:
            # virtual topology (tests / dry-run on fewer chips): tile devices
            devs = np.tile(devs, -(-need // devs.size))
        devs = devs[:need]
        # axis order outer→inner: pp (cross-slice ok) → dp → sharding → sep →
        # mp (innermost: highest-bandwidth ICI neighbors)
        return Mesh(devs.reshape(shape), axes)

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def _make_axis_group(self, paddle_axis: str, mesh_axis: str) -> Group:
        coord = self._topo.get_coord(self.global_rank)
        idx = dict(zip(self._topo.get_hybrid_group_names(), coord))
        ranks = [r for r in self._topo.get_comm_list(paddle_axis)
                 if self.global_rank in r]
        my = ranks[0] if ranks else [self.global_rank]
        pg = ProcessGroupXLA(my, group_id=hash(paddle_axis) % 10000,
                             axis_name=mesh_axis, mesh=self._mesh)
        return Group(my.index(self.global_rank), pg.group_id, my, pg,
                     name=f"{paddle_axis}_group")

    # ------------------------------------------------- degrees / ranks (API)
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._dp_degree > 1:
            return "data_parallel"
        if self._sharding_degree > 1 and self._mp_degree == 1 and \
                self._pp_degree == 1:
            return "sharding_parallel"
        if self._mp_degree > 1 and self._pp_degree == 1:
            return "tensor_parallel"
        if self._pp_degree > 1:
            return "pipeline_parallel"
        return "data_parallel"

    def _coord(self):
        return dict(zip(self._topo.get_hybrid_group_names(),
                        self._topo.get_coord(self.global_rank)))

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord()["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord()["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    # pipeline
    def get_stage_id(self):
        return self._coord()["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord()["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    # sep (sequence/context parallel)
    def get_sep_parallel_rank(self):
        return self._coord().get("sep", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    # fused comm checks
    def get_check_parallel_group(self, *a, **k):
        return self._groups["model"]

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)
