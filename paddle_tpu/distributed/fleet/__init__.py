"""Fleet facade. Parity: python/paddle/distributed/fleet/fleet.py
(fleet.init / distributed_model / distributed_optimizer / worker APIs).
"""
from __future__ import annotations

from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from . import meta_parallel
from .utils import recompute_mod
from .utils.recompute_mod import recompute, recompute_sequential

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "is_first_worker", "barrier_worker",
           "recompute", "CommunicateTopology", "HybridCommunicateGroup"]

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    from ..parallel import init_parallel_env
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
        dims=(hc.get("dp_degree", 1), hc.get("pp_degree", 1),
              hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
              hc.get("mp_degree", 1)))
    hcg = HybridCommunicateGroup(topo)
    _fleet_state.update(strategy=strategy, hcg=hcg, initialized=True)
    # TP dropout determinism (reference: tensor_init_seed)
    seed = strategy.tensor_parallel_configs.get("tensor_init_seed", -1)
    if hc.get("mp_degree", 1) > 1:
        from ...core.rng import model_parallel_random_seed
        model_parallel_random_seed(seed if seed > 0 else 100)
    return _FleetNS


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _fleet_state["hcg"] is None:
        init()
    return _fleet_state["hcg"]


def _get_strategy() -> DistributedStrategy:
    return _fleet_state["strategy"] or DistributedStrategy()


def distributed_model(model):
    """Wrap per the topology (reference: fleet.distributed_model →
    DataParallel / TensorParallel / PipelineParallel / ShardingParallel)."""
    hcg = get_hybrid_communicate_group()
    strategy = _get_strategy()
    from .meta_parallel.parallel_layers import (TensorParallel,
                                                ShardingParallel)
    from .meta_parallel.pipeline_parallel import (PipelineParallel,
                                                  PipelineParallelWithInterleave)
    from .meta_parallel.pp_layers import PipelineLayer
    from ...framework.layer_helpers import DataParallel

    if hcg.get_pipe_parallel_world_size() > 1 or isinstance(model, PipelineLayer):
        if (getattr(model, "_num_virtual_pipeline_stages", None) or 1) > 1:
            return PipelineParallelWithInterleave(model, hcg, strategy)
        return PipelineParallel(model, hcg, strategy)
    mode = hcg.get_parallel_mode()
    if mode == "tensor_parallel":
        return TensorParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy)
    if mode == "data_parallel" and hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model


def distributed_optimizer(optimizer, strategy=None):
    from ...nn.layer.layers import in_dynamic_mode
    if not in_dynamic_mode():
        # static graph: strategy flags select program-rewrite passes
        # (reference: fleet._minimize → meta-optimizer pass stack)
        from .meta_optimizers.static_meta import StaticMetaOptimizer
        return StaticMetaOptimizer(optimizer, strategy or _get_strategy(),
                                   _fleet_state.get("hcg"))
    from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
        HybridParallelOptimizer)
    hcg = get_hybrid_communicate_group()
    return HybridParallelOptimizer(optimizer, hcg, strategy or _get_strategy())


def worker_index() -> int:
    from ..parallel import get_rank
    return get_rank()


def worker_num() -> int:
    from ..parallel import get_world_size
    return get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    from ..communication.ops import barrier
    barrier()


class _FleetNSType:
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)
    DistributedStrategy = DistributedStrategy

    @staticmethod
    def get_hybrid_communicate_group():
        return get_hybrid_communicate_group()


_FleetNS = _FleetNSType()
