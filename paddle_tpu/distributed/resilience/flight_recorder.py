"""Distributed flight recorder: per-rank collective event rings, hang
dumps, and cross-rank desync diagnosis.

Parity target: torch's NCCL flight recorder (``TORCH_NCCL_TRACE_BUFFER_
SIZE`` / ``TORCH_NCCL_DUMP_ON_TIMEOUT``) — when a gang wedges, the
supervisor must be able to say *which collective* diverged and *which
rank* is the straggler, not just tail a workerlog. Rebuilt here on the
repo's own TCPStore + telemetry plumbing:

  * ``FlightRecorder`` — a bounded per-rank ring (``PADDLE_FLIGHT_
    RECORDER`` sets the size; default on in multi-process jobs, ``0``
    disables with ONE branch per event and zero clock reads) recording
    every collective/rpc entry and exit: monotonic seq number, per-
    process-group seq (the cross-rank alignment key — SPMD ranks issue
    the same collectives in the same order per group), op kind, payload
    shape/dtype/bytes, start/end timestamps, status
    ``in_flight | done | error``.
  * ONE instrumentation choke point — ``instrumented()`` (decorator)
    and ``record_span()`` (context manager) — that ``communication/
    ops.py``, ``communication/group.py``, ``parallel.py::
    all_reduce_gradients``, ``Watchdog.monitored_barrier`` and
    ``rpc.py`` all route through. Nested entries record only the
    OUTERMOST op (``all_gather_object`` is one logical collective, not
    three), and tracer-backed payloads are skipped entirely (a traced
    collective is compiled into an XLA program; recording at trace time
    would desynchronize seq numbers across ranks whose jit caches
    differ). ``tools/check_collective_surface.py`` asserts structurally
    that no public collective bypasses the choke point.
  * Hang dumps — ``dump()`` writes ``flightdump.<rank>.<generation>.
    json`` (dir: ``PADDLE_FLIGHT_DUMP_DIR``, the gang supervisor points
    it at its log dir): the recorder tail, all-thread Python stacks
    (``sys._current_frames`` + a raw ``faulthandler`` section),
    watchdog gauges (heartbeat ages, restart generation — the dump is
    self-describing without supervisor context), and the runtime
    histogram registry. Triggered on watchdog ``PeerFailureError``,
    wedged-rank escalation (exit 117), and supervisor SIGTERM.
  * Cross-rank diagnosis — ``diagnose_dir()`` aggregates the dumps
    into the desync verdict ("rank 0 in_flight in all_reduce seq=4;
    rank 1 completed seq=3, never entered") naming the desynced
    collective, the straggler ranks, ranks whose dump is missing, and
    the straggler's in-collective stack. The gang supervisor and
    ``tools/flight_report.py`` share this ONE implementation, so the
    offline report reproduces the supervisor's diagnosis byte-for-byte.
  * Cluster aggregation — each rank's watchdog publisher piggybacks a
    small recorder snapshot onto TCPStore (``fr/<rank>`` keys, same
    pattern as heartbeats); ``cluster_snapshot()`` on any rank reads
    them all. Per-op wait-time histograms feed ``inference/telemetry``'s
    ``runtime_histogram`` registry, so rank-level Prometheus exposition
    comes for free; ``export_chrome_tracing()`` renders the dumps as a
    pid-per-rank Perfetto timeline over ``profiler.ChromeTrace``.

Import-light by design (stdlib only at module import): the launcher and
the watchdog failure path load this; telemetry/profiler are pulled in
lazily at the first recorded exit / export.
"""
from __future__ import annotations

import functools
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from contextlib import contextmanager

__all__ = ["FlightRecorder", "DEFAULT_RING", "DUMP_SCHEMA",
           "configure", "recorder", "reset", "instrumented",
           "record_span", "instrumented_ops", "runtime_hist_name",
           "dump_on_failure", "install_signal_dump", "dump_path",
           "load_dumps", "diagnose", "diagnose_dir", "publish_snapshot",
           "maybe_publish", "cluster_snapshot", "export_chrome_tracing"]

DEFAULT_RING = 256
DUMP_SCHEMA = "paddle_tpu.flightdump.v1"
ENV_RING = "PADDLE_FLIGHT_RECORDER"
ENV_DUMP_DIR = "PADDLE_FLIGHT_DUMP_DIR"
SNAPSHOT_KEY_PREFIX = "fr/"
STACK_TAIL_FRAMES = 12          # frames of the straggler stack in the report
_RUNTIME_HIST_PREFIX = "paddle_runtime_collective_seconds"

_SKIP = object()                # sentinel: tracer-backed payload, don't record


def runtime_hist_name(op: str) -> str:
    """Stable runtime-registry histogram name for one op kind (appears
    in ``telemetry.runtime_prometheus()`` once the op has recorded an
    exit; ``tools/check_metrics_surface.py`` pins the mapping)."""
    return f"{_RUNTIME_HIST_PREFIX}_{op}"


def _telemetry():
    """Lazy runtime-metrics registry (same pattern as rpc.py): the
    recorder must not drag numpy in at import, and must never fail on
    metrics."""
    global _TELE
    if _TELE is None:
        try:
            from ...inference import telemetry as _t
            _TELE = _t
        except Exception:
            _TELE = False
    return _TELE or None


_TELE = None


def _fault():
    """Lazy fault-injection harness (PADDLE_FI_HANG inside a collective
    rides the choke point — the desync e2e's hook)."""
    global _FAULT
    if _FAULT is None:
        try:
            from ...testing import fault as _f
            _FAULT = _f
        except Exception:
            _FAULT = False
    return _FAULT or None


_FAULT = None


# ------------------------------------------------------------------ recorder
class FlightRecorder:
    """Bounded per-rank ring of collective/rpc events.

    ``ring == 0`` disables collection: ``start``/``end`` return after
    ONE branch with no clock reads (pinned by a counting-clock test,
    same discipline as telemetry-off). In-flight events are tracked in
    a side dict so a hung collective stays visible in ``tail()`` even
    after later events evicted it from the ring.
    """

    def __init__(self, ring=None, rank=None, world=None, clock=None):
        if ring is None:
            ring = int(os.environ.get(ENV_RING, str(DEFAULT_RING)))
        if ring < 0:
            raise ValueError(f"flight recorder ring must be >= 0, "
                             f"got {ring}")
        self.ring = int(ring)
        self.enabled = self.ring > 0
        self.rank = int(rank if rank is not None
                        else os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
        self.world = int(world if world is not None
                         else os.environ.get("PADDLE_TRAINERS_NUM", "1")
                         or 1)
        # time.monotonic, NOT perf_counter: dump headers stamp t_mono
        # with the same clock, so "how long has this op been in flight"
        # is dump.t_mono - ev.t_start with no cross-clock skew
        self.clock = clock or time.monotonic
        self.events = deque(maxlen=max(self.ring, 1))
        self._in_flight = {}            # seq -> event dict
        self._gseq = {}                 # group -> per-group seq counter
        self._seq = 0
        # RLock, not Lock: the SIGTERM dump handler runs on the MAIN
        # thread at a bytecode boundary, which can land while that same
        # thread's interrupted start()/end() frame holds the lock — a
        # plain Lock would deadlock the dump (and the exit) against it
        self._lock = threading.RLock()
        self._dump_path = None          # set by the first dump (dump-once)

    # ------------------------------------------------------------- recording
    def start(self, op, group="default", kind="collective", shape=None,
              dtype=None, nbytes=None, note=None):
        """Record a collective/rpc ENTRY; returns the event (hand it to
        ``end``), or None when disabled."""
        if not self.enabled:
            return None
        t = self.clock()
        with self._lock:
            self._seq += 1
            gseq = self._gseq.get(group, 0) + 1
            self._gseq[group] = gseq
            ev = {"seq": self._seq, "gseq": gseq, "op": op,
                  "group": group, "kind": kind, "status": "in_flight",
                  "t_start": t, "t_end": None}
            if shape is not None:
                ev["shape"] = list(shape)
            if dtype is not None:
                ev["dtype"] = str(dtype)
            if nbytes is not None:
                ev["nbytes"] = int(nbytes)
            if note is not None:
                ev["note"] = note
            self.events.append(ev)
            self._in_flight[ev["seq"]] = ev
        return ev

    def end(self, ev, error=None):
        """Record the matching EXIT; feeds the per-op wait-time
        histogram in the runtime registry."""
        if ev is None:
            return
        t = self.clock()
        with self._lock:
            ev["t_end"] = t
            ev["status"] = "done" if error is None else "error"
            if error is not None:
                ev["error"] = repr(error)
            self._in_flight.pop(ev["seq"], None)
        if ev["kind"] == "collective":
            tele = _telemetry()
            if tele is not None:
                tele.runtime_histogram(
                    runtime_hist_name(ev["op"])).observe(t - ev["t_start"])

    def tail(self):
        """Ring contents (seq order), merged with any in-flight events
        the ring already evicted — a hung op is never dropped."""
        with self._lock:
            evs = {ev["seq"]: ev for ev in self.events}
            evs.update(self._in_flight)
        return [dict(evs[s]) for s in sorted(evs)]

    def snapshot(self):
        """Small JSON-able state summary — published to TCPStore by the
        watchdog's heartbeat publisher and aggregated by
        ``cluster_snapshot()`` (keep it heartbeat-sized: no stacks, no
        event bodies)."""
        with self._lock:
            groups = {}
            for ev in self._in_flight.values():
                g = groups.setdefault(ev["group"], {})
                g["in_flight_op"] = ev["op"]
                g["in_flight_seq"] = ev["gseq"]
            for grp, gseq in self._gseq.items():
                groups.setdefault(grp, {})["seq"] = gseq
            return {"rank": self.rank, "world": self.world,
                    "generation": _generation(),
                    "events_recorded": self._seq,
                    "in_flight": len(self._in_flight),
                    "groups": groups}

    # ----------------------------------------------------------------- dumps
    def dump_payload(self, reason="manual"):
        """The full dump dict (separable from file IO for tests): ring
        tail, all-thread stacks, watchdog gauges, runtime registry."""
        t_mono = self.clock()
        payload = {
            "schema": DUMP_SCHEMA,
            "rank": self.rank,
            "world": self.world,
            "generation": _generation(),
            "pid": os.getpid(),
            "reason": reason,
            "t_wall": time.time(),
            "t_mono": t_mono,
            "ring": self.ring,
            "events_recorded": self._seq,
            "events": self.tail(),
            "watchdog": _watchdog_state(),
            "stacks": _thread_stacks(),
            "faulthandler": _faulthandler_text(),
        }
        tele = _telemetry()
        if tele is not None:
            try:
                payload["runtime_metrics"] = tele.runtime_registry_snapshot()
            except Exception:
                payload["runtime_metrics"] = None
        return payload

    def dump(self, path=None, reason="manual", force=False):
        """Write the flight dump (atomic: tmp + rename). Dump-once by
        default: the FIRST failure's view is the interesting one, and
        cascading triggers (watchdog failure, then SIGTERM from the
        supervisor reaping the gang) must not overwrite it."""
        if self._dump_path is not None and not force:
            return self._dump_path
        if path is None:
            path = dump_path(self.rank, _generation())
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.dump_payload(reason), f, default=str)
        os.replace(tmp, path)
        self._dump_path = path
        return path


def _generation() -> int:
    return int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)


def _watchdog_state():
    """The local watchdog's gauges + recorded failure — the dump header
    must be self-describing without the supervisor's context (ISSUE:
    heartbeat ages and restart generation in every dump)."""
    try:
        from .watchdog import current_watchdog
        wd = current_watchdog()
    except Exception:
        return None
    if wd is None:
        return None
    try:
        return {"gauges": wd.gauges(),
                "failure": str(wd.failure) if wd.failure else None,
                "failure_ranks": list(wd.failure.ranks)
                if wd.failure is not None else []}
    except Exception:
        return None


def _thread_stacks():
    """All-thread Python stacks as structured frames; the MAIN thread
    (the one wedged inside a collective) is tagged so the diagnosis can
    print its in-collective stack."""
    names = {t.ident: t.name for t in threading.enumerate()}
    main_id = threading.main_thread().ident
    stacks = {}
    for tid, frame in sys._current_frames().items():
        label = names.get(tid, "unknown")
        key = f"{label} (tid {tid})" + (" [main]" if tid == main_id else "")
        stacks[key] = [
            {"file": fs.filename, "line": fs.lineno, "func": fs.name,
             "code": fs.line or ""}
            for fs in traceback.extract_stack(frame)]
    return stacks


def _faulthandler_text():
    """Raw faulthandler dump (C-level view of every thread) — catches
    what the pure-Python walk can't when the interpreter state is
    damaged. faulthandler writes through a real fd, so round-trip via a
    temp file."""
    try:
        import faulthandler
        import tempfile
        with tempfile.TemporaryFile(mode="w+") as tf:
            faulthandler.dump_traceback(file=tf, all_threads=True)
            tf.seek(0)
            return tf.read()
    except Exception:
        return ""


# ----------------------------------------------------------- module recorder
_UNSET = object()
_REC: list = [_UNSET]


def _init_from_env(world_hint=None):
    """Default policy: explicitly set PADDLE_FLIGHT_RECORDER wins
    (``0`` = off, N = ring size); unset = on with the default ring in
    multi-process jobs, off single-process. The world comes from the
    caller when known (``init_parallel_env`` passes the authoritative
    count, covering jax-native launches where PADDLE_TRAINERS_NUM is
    never set), else from the env contract."""
    ring_env = os.environ.get(ENV_RING)
    ring = None
    if ring_env is not None and ring_env != "":
        # defensive parse: recorder() is called lazily from inside the
        # first collective, so a malformed env var must degrade to the
        # default policy with a clear warning — not kill the job with a
        # traceback pointing into an all_reduce
        try:
            ring = int(ring_env)
            if ring < 0:
                raise ValueError(ring_env)
        except ValueError:
            import logging
            logging.warning(
                "paddle_tpu flight recorder: ignoring malformed %s=%r "
                "(expected a non-negative integer ring size); using the "
                "default policy", ENV_RING, ring_env)
            ring = None
    if ring is None:
        world = world_hint
        if world is None:
            try:
                world = int(os.environ.get(
                    "PADDLE_TRAINERS_NUM",
                    os.environ.get("JAX_NUM_PROCESSES", "1")) or 1)
            except ValueError:
                world = 1
        ring = DEFAULT_RING if world > 1 else 0
    rec = FlightRecorder(ring=ring) if ring > 0 else None
    _REC[0] = rec
    return rec


def recorder() -> FlightRecorder | None:
    """The process-global recorder; None when disabled (the hot path's
    single branch)."""
    rec = _REC[0]
    if rec is _UNSET:
        rec = _init_from_env()
    return rec


def configure(ring=None, rank=None, world=None, clock=None):
    """(Re)build the process-global recorder with authoritative values
    (``init_parallel_env`` calls this once rank/world are known; tests
    call it directly). Returns the recorder, or None when disabled."""
    if ring is None:
        _REC[0] = _UNSET
        rec = _init_from_env(world_hint=world)
        if rec is not None and (rank is not None or world is not None):
            rec.rank = int(rank if rank is not None else rec.rank)
            rec.world = int(world if world is not None else rec.world)
        return rec
    rec = FlightRecorder(ring=ring, rank=rank, world=world, clock=clock) \
        if ring > 0 else None
    _REC[0] = rec
    return rec


def reset():
    """Drop the cached recorder (tests): the next ``recorder()`` call
    re-reads the env."""
    _REC[0] = _UNSET


# --------------------------------------------------------------- choke point
_tls = threading.local()


def _is_tracer(x) -> bool:
    # duck-typed (no jax import): every jax Tracer carries _trace;
    # eager ArrayImpl / numpy arrays do not
    return hasattr(x, "_trace")


def _payload_of(args, kwargs):
    """Best-effort payload introspection: the first Tensor-like
    (``._data``) or array-like (``.shape``/``.dtype``) positional, or a
    list of them (bytes summed). Returns ``_SKIP`` for tracer-backed
    payloads — traced collectives are compiled, not eager events."""
    def _arr(x):
        data = getattr(x, "_data", x)
        if _is_tracer(data):
            return _SKIP
        if hasattr(data, "shape") and hasattr(data, "dtype"):
            return data
        return None

    # kwargs too: `all_reduce(tensor=x)` must hit the same tracer
    # guard as the positional form, or traced calls record per-compile
    # instead of per-execution and desynchronize the seq numbers
    for a in tuple(args[:4]) + tuple(kwargs.values())[:4]:
        if isinstance(a, (list, tuple)) and a:
            first = _arr(a[0])
            if first is _SKIP:
                return _SKIP
            if first is not None:
                per = _nbytes(first)
                return {"shape": first.shape, "dtype": first.dtype,
                        "nbytes": per * len(a) if per is not None
                        else None}
        else:
            arr = _arr(a)
            if arr is _SKIP:
                return _SKIP
            if arr is not None:
                return {"shape": arr.shape, "dtype": arr.dtype,
                        "nbytes": _nbytes(arr)}
    return {}


def _nbytes(arr):
    try:
        return int(arr.size) * int(arr.dtype.itemsize)
    except Exception:
        return None


def _group_of(args, kwargs):
    """Group NAME for the event — the cross-rank alignment key, so it
    must be derived from call-site data every rank shares (group names
    are assigned in program order, identical across SPMD ranks)."""
    g = kwargs.get("group")
    cands = (g,) + tuple(args[:4]) if g is not None else tuple(args[:4])
    for c in cands:
        if c is None:
            continue
        if hasattr(c, "pg") and hasattr(c, "name"):        # Group
            return c.name
        if hasattr(c, "group_id") and hasattr(c, "ranks"):  # ProcessGroupXLA
            return f"pg{c.group_id}"
    return "default"


@contextmanager
def record_span(op, kind="collective", group="default", payload=None,
                note=None):
    """THE instrumentation choke point (context-manager form): every
    public collective/rpc entry in the runtime routes through here (or
    through the ``instrumented`` decorator built on it). Nested spans
    record only the outermost op; disabled mode is one branch."""
    rec = recorder()
    if rec is None:
        yield None
        return
    if getattr(_tls, "depth", 0):
        yield None                      # nested: outer op owns the event
        return
    if kind == "collective":
        f = _fault()
        if f is not None:
            # the desync-e2e hook: PADDLE_FI_AT_POINT=collective hangs
            # a rank HERE, before the entry is recorded — "never
            # entered seq N" is exactly what the diagnosis must name
            f.inject("collective")
    ev = rec.start(op, group=group, kind=kind, note=note,
                   **(payload or {}))
    _tls.depth = 1
    try:
        yield ev
    except BaseException as e:
        rec.end(ev, error=e)
        raise
    else:
        rec.end(ev)
    finally:
        _tls.depth = 0


_known_ops: set = set()


def instrumented(op, kind="collective"):
    """Decorator form of the choke point for module-level collectives
    (``communication/ops.py`` etc.): payload and group are introspected
    from the call args; tracer-backed calls skip recording entirely.
    ``tools/check_collective_surface.py`` asserts every public
    collective carries this decorator."""
    _known_ops.add(op)

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            rec = recorder()
            if rec is None or getattr(_tls, "depth", 0):
                return fn(*args, **kwargs)
            payload = _payload_of(args, kwargs)
            if payload is _SKIP:
                return fn(*args, **kwargs)
            with record_span(op, kind=kind,
                             group=_group_of(args, kwargs),
                             payload=payload):
                return fn(*args, **kwargs)
        wrapper.__flight_recorder_op__ = op
        return wrapper
    return deco


def instrumented_ops():
    """Every op kind registered through ``instrumented`` in this
    process (the structural checks iterate it)."""
    return sorted(_known_ops)


# ----------------------------------------------------------------- triggers
def dump_on_failure(reason):
    """Best-effort module-level dump (the watchdog failure path calls
    this — it must never be able to break failure handling)."""
    rec = recorder()
    if rec is None:
        return None
    try:
        return rec.dump(reason=reason)
    except Exception:
        return None


def install_signal_dump():
    """SIGTERM handler: dump, then chain to the previous handler (or
    exit 128+15 when the default would have terminated us). Installed
    by ``init_parallel_env`` in multi-process jobs — the gang
    supervisor SIGTERMs survivors when reaping a failed gang, and each
    must leave its flight dump behind. Main-thread only (signal API
    contract)."""
    rec = recorder()
    if rec is None:
        return False
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        try:
            rec.dump(reason="sigterm")
        except Exception:
            pass
        if prev is signal.SIG_IGN:
            return                  # the host app chose to ignore SIGTERM
        if callable(prev):
            prev(signum, frame)
        else:                       # SIG_DFL (or non-Python handler):
            os._exit(128 + signum)  # preserve die-on-SIGTERM semantics

    signal.signal(signal.SIGTERM, _handler)
    return True


# ---------------------------------------------------------------- dump files
def dump_path(rank, generation, dump_dir=None) -> str:
    d = dump_dir or os.environ.get(ENV_DUMP_DIR) or "."
    return os.path.join(d, f"flightdump.{rank}.{generation}.json")


def load_dumps(dump_dir, generation=None):
    """Parse every ``flightdump.<rank>.<generation>.json`` in the dir.
    Returns ``(generation, {rank: dump}, {rank: error-string})`` —
    unparsable files land in the error map so the diagnosis can NAME
    ranks that crashed mid-dump instead of silently omitting them.
    ``generation=None`` picks the newest generation present."""
    found = {}                          # generation -> {rank: path}
    try:
        names = os.listdir(dump_dir)
    except OSError:
        names = []
    for name in names:
        parts = name.split(".")
        if len(parts) != 4 or parts[0] != "flightdump" or parts[3] != "json":
            continue
        try:
            rank, gen = int(parts[1]), int(parts[2])
        except ValueError:
            continue
        found.setdefault(gen, {})[rank] = os.path.join(dump_dir, name)
    if not found:
        return generation or 0, {}, {}
    gen = max(found) if generation is None else int(generation)
    dumps, errors = {}, {}
    for rank, path in sorted(found.get(gen, {}).items()):
        try:
            with open(path) as f:
                dumps[rank] = json.load(f)
        except (OSError, ValueError) as e:
            errors[rank] = f"unparsable: {e}"
    return gen, dumps, errors


# ----------------------------------------------------------------- diagnosis
def _rank_group_state(dump, group):
    """(last_entered_gseq, in_flight event or None, last op) for one
    rank in one group, from its dump's event list."""
    last, in_flight, last_op = 0, None, None
    for ev in dump.get("events", ()):
        if ev.get("kind") != "collective" or ev.get("group") != group:
            continue
        if ev["gseq"] >= last:
            last = ev["gseq"]
            last_op = ev["op"]
        if ev["status"] == "in_flight":
            if in_flight is None or ev["gseq"] > in_flight["gseq"]:
                in_flight = ev
    return last, in_flight, last_op


def diagnose(dumps, errors=None, world=None, generation=0,
             expected_ranks=None):
    """Aggregate per-rank dumps into the cross-rank verdict.

    Returns ``(text, struct)``. The text is DETERMINISTIC given the
    dump contents (elapsed times come from each dump's own clock pair,
    never from report time), so the supervisor's report and
    ``tools/flight_report.py`` are byte-for-byte identical.

    ``expected_ranks`` bounds which ranks may be declared
    missing-dump stragglers: a multi-node supervisor only sees its own
    node's dump dir, so it must pass the ranks it spawned — remote
    ranks dumping to other hosts are not "crashed before dumping".
    Default: every rank in ``world``.
    """
    errors = dict(errors or {})
    if world is None:
        world = max([d.get("world", 0) for d in dumps.values()]
                    + [max(dumps, default=-1) + 1,
                       max(errors, default=-1) + 1])
    if expected_ranks is None:
        expected_ranks = range(world)
    ranks_with = sorted(dumps)
    missing = [r for r in expected_ranks
               if r not in dumps and r not in errors]
    lines = [f"flight recorder: cross-rank diagnosis "
             f"(generation {generation}, world {world})",
             f"  dumps: ranks {ranks_with}"]
    if missing or errors:
        parts = [f"rank {r} (no dump file — crashed before dumping, or "
                 "recorder disabled)" for r in missing]
        parts += [f"rank {r} ({errors[r]})" for r in sorted(errors)]
        lines.append("  missing dumps: " + ", ".join(parts))

    groups = sorted({ev.get("group") for d in dumps.values()
                     for ev in d.get("events", ())
                     if ev.get("kind") == "collective"})
    stragglers: set = set()
    stuck = None
    desync = False
    group_struct = {}
    for grp in groups:
        states = {r: _rank_group_state(d, grp) for r, d in dumps.items()}
        frontier = max((s[0] for s in states.values()), default=0)
        in_flight_any = any(s[1] is not None for s in states.values())
        aligned = (not in_flight_any
                   and len({s[0] for s in states.values()}) <= 1)
        grp_stragglers = set(
            r for r, (last, fl, _) in states.items()
            if last < frontier or (fl is not None
                                   and fl["gseq"] < frontier))
        # async-completion case: a rank still INSIDE a collective that
        # some peer has completed and LEFT (nothing of its own in
        # flight) is a straggler too — the peers finished seq N and
        # moved on or exited; this rank never did. Kept distinct from
        # "every rank in_flight at the same seq", which has no single
        # culprit.
        if any(last >= frontier and fl is None
               for last, fl, _ in states.values()):
            grp_stragglers |= {r for r, (last, fl, _) in states.items()
                               if fl is not None
                               and fl["gseq"] >= frontier}
        grp_stragglers = sorted(grp_stragglers)
        per_rank = {}
        if aligned:
            lines.append(f"  group '{grp}': aligned at seq {frontier}")
            group_struct[grp] = {"aligned": True, "seq": frontier}
            continue
        desync = True
        # the stuck collective: the earliest op still in flight, else
        # the frontier op the stragglers never entered
        flights = sorted(((s[1]["gseq"], r, s[1])
                          for r, s in states.items() if s[1] is not None))
        if flights:
            stuck_seq, _, stuck_ev = flights[0]
            stuck_op = stuck_ev["op"]
        else:
            stuck_seq = frontier
            stuck_op = next((s[2] for s in states.values()
                             if s[0] == frontier and s[2]), "?")
        lines.append(f"  group '{grp}': desync in {stuck_op} "
                     f"at seq {stuck_seq}")
        for r in sorted(states):
            last, fl, last_op = states[r]
            dump_t = dumps[r].get("t_mono", 0.0)
            if fl is not None:
                waited = max(dump_t - fl["t_start"], 0.0)
                extra = " (waiting on stragglers)" \
                    if (fl["gseq"] >= frontier and grp_stragglers
                        and r not in grp_stragglers) else ""
                lines.append(f"    rank {r}: in_flight in {fl['op']} "
                             f"seq={fl['gseq']} for {waited:.2f}s{extra}")
                per_rank[r] = {"status": "in_flight", "op": fl["op"],
                               "seq": fl["gseq"],
                               "waited_s": round(waited, 2)}
            elif last < frontier:
                lines.append(f"    rank {r}: completed seq={last}, "
                             f"never entered {stuck_op} seq={stuck_seq}")
                per_rank[r] = {"status": "never_entered", "seq": last}
            else:
                lines.append(f"    rank {r}: completed seq={last} "
                             f"({last_op}) and left the collective")
                per_rank[r] = {"status": "done", "seq": last}
        # collective-order mismatch (rank A in send while B in
        # all_reduce): a desynced program order, worth its own line
        ops_in_flight = {s[1]["op"] for s in states.values()
                         if s[1] is not None and s[1]["gseq"] == stuck_seq}
        if len(ops_in_flight) > 1:
            lines.append("    op mismatch at seq="
                         f"{stuck_seq}: {sorted(ops_in_flight)} — ranks "
                         "issued different collectives (desynced "
                         "program order)")
        stragglers.update(grp_stragglers)
        if stuck is None:
            stuck = {"group": grp, "op": stuck_op, "seq": stuck_seq}
        group_struct[grp] = {"aligned": False, "op": stuck_op,
                             "seq": stuck_seq,
                             "stragglers": grp_stragglers,
                             "per_rank": per_rank}

    # ranks wedged with an rpc (or other non-collective span) open
    for r in sorted(dumps):
        for ev in dumps[r].get("events", ()):
            if ev.get("kind") != "collective" \
                    and ev.get("status") == "in_flight":
                waited = max(dumps[r].get("t_mono", 0.0) - ev["t_start"],
                             0.0)
                note = f" ({ev['note']})" if ev.get("note") else ""
                lines.append(f"  rank {r}: {ev['kind']} in_flight in "
                             f"{ev['op']}{note} group={ev['group']} "
                             f"for {waited:.2f}s")

    if not groups and dumps:
        lines.append("  no collective events recorded")
    elif not desync and dumps:
        lines.append("  no cross-rank desync detected (all groups "
                     "aligned)")
    # missing-dump ranks are prime straggler suspects too: a rank that
    # died or wedged before dumping never entered the stuck collective
    all_missing = sorted(set(missing) | set(errors))
    if desync:
        stragglers.update(all_missing)
    if desync and not stragglers:
        lines.append("  stragglers: none identified — every rank is "
                     "in_flight at the same seq (the collective itself "
                     "is wedged: transport, or a peer outside these "
                     "dumps)")
    elif stragglers:
        lines.append("  stragglers: " + ", ".join(
            f"rank {r}" for r in sorted(stragglers)))

    # watchdog verdicts from the dump headers (who flagged whom)
    flags = []
    for r in sorted(dumps):
        wd = dumps[r].get("watchdog") or {}
        if wd.get("failure_ranks"):
            flags.append(f"rank {r} -> {wd['failure_ranks']}")
    if flags:
        lines.append("  watchdog flags: " + "; ".join(flags))

    # the straggler's in-collective stack, straight from its dump
    for r in sorted(stragglers):
        stack = _main_stack(dumps.get(r))
        if not stack:
            continue
        lines.append(f"  straggler rank {r} main-thread stack "
                     "(most recent call last):")
        for fs in stack[-STACK_TAIL_FRAMES:]:
            base = os.path.basename(fs.get("file", "?"))
            lines.append(f"    {base}:{fs.get('line')} "
                         f"{fs.get('func')}: {fs.get('code', '')}")

    struct = {"generation": generation, "world": world,
              "desync": desync, "ranks_with_dump": ranks_with,
              "ranks_missing_dump": all_missing,
              "missing_dump_errors": {str(r): errors[r]
                                      for r in sorted(errors)},
              "stragglers": sorted(stragglers), "stuck": stuck,
              "groups": group_struct}
    return "\n".join(lines), struct


def _main_stack(dump):
    if not dump:
        return None
    for key, frames in (dump.get("stacks") or {}).items():
        if key.endswith("[main]"):
            return frames
    return None


def diagnose_dir(dump_dir, world=None, generation=None,
                 expected_ranks=None):
    """Diagnose straight from a dump directory — the ONE code path the
    gang supervisor's failure report and ``tools/flight_report.py``
    both call (byte-for-byte identical output is the contract)."""
    gen, dumps, errors = load_dumps(dump_dir, generation=generation)
    return diagnose(dumps, errors=errors, world=world, generation=gen,
                    expected_ranks=expected_ranks)


# --------------------------------------------------------- cluster snapshot
def publish_snapshot(store, rec=None):
    """Publish this rank's recorder snapshot to ``fr/<rank>`` (the
    watchdog's heartbeat publisher piggybacks this every beat)."""
    rec = rec if rec is not None else recorder()
    if rec is None or not rec.enabled:
        return False
    store.set(f"{SNAPSHOT_KEY_PREFIX}{rec.rank}",
              json.dumps(rec.snapshot()).encode())
    return True


def maybe_publish(store):
    """Best-effort ``publish_snapshot`` (heartbeat-loop safe: never
    raises, never publishes when disabled)."""
    try:
        return publish_snapshot(store)
    except Exception:
        return False


def cluster_snapshot(store_factory=None, world=None):
    """Rank-0 (or any rank's) cluster-wide view: every rank's published
    recorder snapshot, aggregated like heartbeats. Defaults ride the
    running watchdog's store; ranks that never published map to None."""
    if store_factory is None or world is None:
        from .watchdog import current_watchdog
        wd = current_watchdog()
        if wd is None:
            raise RuntimeError(
                "cluster_snapshot needs a store_factory + world when no "
                "watchdog is running")
        store_factory = store_factory or wd._store_factory
        world = world if world is not None else wd.world
    store = store_factory(5.0)
    try:
        out = {}
        for r in range(int(world)):
            raw = store.get(f"{SNAPSHOT_KEY_PREFIX}{r}")
            out[r] = json.loads(raw.decode()) if raw else None
        return out
    finally:
        try:
            store.close()
        except Exception:
            pass


# ------------------------------------------------------------------ perfetto
def export_chrome_tracing(dump_dir_or_dumps, path, generation=None):
    """Render flight dumps as a pid-per-rank Chrome/Perfetto trace over
    ``profiler.ChromeTrace`` (PR 8's shared event model): pid = rank,
    one 'collectives' track and one 'rpc' track per rank, in-flight
    events drawn to each rank's dump time with status args. Per-event
    monotonic timestamps are rebased to wall time through each dump's
    own (t_wall, t_mono) anchor pair, so ranks line up cross-process."""
    if isinstance(dump_dir_or_dumps, dict):
        dumps = dump_dir_or_dumps
    else:
        _, dumps, _ = load_dumps(dump_dir_or_dumps, generation=generation)
    if not dumps:
        raise ValueError("export_chrome_tracing: no flight dumps found")
    from ...profiler import ChromeTrace        # lazy: pulls jax
    tr = ChromeTrace()
    anchors = {}
    for r, d in sorted(dumps.items()):
        anchors[r] = d.get("t_wall", 0.0) - d.get("t_mono", 0.0)
        tr.process(r, f"rank {r} flight recorder")
        tr.thread(r, 0, "collectives")
        tr.thread(r, 1, "rpc")
    walls = [a + ev["t_start"]
             for r, d in dumps.items() for ev in d.get("events", ())
             for a in (anchors[r],)]
    base = min(walls) if walls else 0.0
    for r, d in sorted(dumps.items()):
        a = anchors[r]
        for ev in d.get("events", ()):
            t0 = a + ev["t_start"] - base
            t1 = a + (ev["t_end"] if ev["t_end"] is not None
                      else d.get("t_mono", ev["t_start"])) - base
            args = {k: ev[k] for k in ("seq", "gseq", "group", "status",
                                       "shape", "dtype", "nbytes",
                                       "note", "error") if k in ev}
            tid = 0 if ev.get("kind") == "collective" else 1
            tr.complete(f"{ev['op']} seq={ev['gseq']}", r, tid,
                        t0 * 1e6, max(t1 - t0, 0.0) * 1e6, args=args)
        tr.instant(f"dump [{d.get('reason', '?')}]", r, 0,
                   (a + d.get("t_mono", 0.0) - base) * 1e6)
    tr.write(path)
    return path
