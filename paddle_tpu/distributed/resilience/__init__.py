"""paddle.distributed resilience layer: heartbeat watchdog + monitored
barrier (parity: ProcessGroupNCCL watchdog / FLAGS_pg_timeout semantics,
realized over the native TCPStore)."""
from .watchdog import (PeerFailureError, Watchdog, start_watchdog,
                       stop_watchdog, check_peer_failure,
                       monitored_barrier, notify_progress,
                       current_watchdog, WATCHDOG_EXIT_CODE)

__all__ = ["PeerFailureError", "Watchdog", "start_watchdog",
           "stop_watchdog", "check_peer_failure", "monitored_barrier",
           "notify_progress", "current_watchdog", "WATCHDOG_EXIT_CODE"]
