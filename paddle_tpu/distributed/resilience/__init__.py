"""paddle.distributed resilience layer: heartbeat watchdog + monitored
barrier (parity: ProcessGroupNCCL watchdog / FLAGS_pg_timeout semantics,
realized over the native TCPStore) + the distributed flight recorder
(parity: torch's NCCL flight recorder — per-rank collective event rings,
hang dumps, cross-rank desync diagnosis)."""
from . import flight_recorder
from .flight_recorder import (FlightRecorder, cluster_snapshot,
                              diagnose_dir)
from .watchdog import (PeerFailureError, Watchdog, start_watchdog,
                       stop_watchdog, check_peer_failure,
                       monitored_barrier, notify_progress,
                       current_watchdog, WATCHDOG_EXIT_CODE)

__all__ = ["PeerFailureError", "Watchdog", "start_watchdog",
           "stop_watchdog", "check_peer_failure", "monitored_barrier",
           "notify_progress", "current_watchdog", "WATCHDOG_EXIT_CODE",
           "flight_recorder", "FlightRecorder", "cluster_snapshot",
           "diagnose_dir"]
