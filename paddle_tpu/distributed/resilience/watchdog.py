"""Heartbeat watchdog over the native TCPStore (csrc/runtime.cc).

Parity: ProcessGroupNCCL's watchdog thread + FLAGS_pg_timeout — a hung or
dead peer must become a TIMELY error on the survivors, not an indefinite
wait inside a collective. Each rank publishes a monotonically increasing
counter at `hb/<rank>` from a daemon publisher thread; a watcher thread
judges peers by counter PROGRESS against its own monotonic clock (no
cross-host wall-clock comparison — NTP skew would eat into the timeout,
same design as fleet.elastic.manager).

On a stale peer the watchdog:
  1. records a PeerFailureError (check_peer_failure() raises it from the
     train-step hook / any host-side control point),
  2. action "raise" (default): async-raises it in the main thread so a
     Python-level loop dies promptly, then — because a rank blocked inside
     a C-level collective never runs bytecode again — hard-exits after
     PADDLE_WATCHDOG_KILL_GRACE_S (WATCHDOG_EXIT_CODE, so the gang
     supervisor sees a clean, attributable failure);
  3. action "flag": records only (in-process tests).

A store that stops answering (the rank-0 host died and took the store
daemon with it) is treated exactly like a stale peer after the same
timeout — "everyone else vanished" and "one peer vanished" must both
unwedge the survivor.

Detection scope: the publisher is a daemon THREAD, so by default the
watchdog catches dead PROCESSES (crash, OOM-kill, os._exit) — a peer
whose main thread is wedged in a collective keeps beating and is NOT
flagged. Opt into main-thread liveness with
PADDLE_WATCHDOG_REQUIRE_PROGRESS_S=<s>: the publisher goes dark once
notify_progress() (called every Optimizer.step) is staler than <s>,
converting a local hang into a missing heartbeat the peers flag. Off by
default because legitimate step gaps (eval, first-step compile) would
read as hangs; size it to a multiple of the slowest expected step.
"""
from __future__ import annotations

import ctypes
import logging
import os
import threading
import time

from ..logjson import log_event
from . import flight_recorder

__all__ = ["PeerFailureError", "Watchdog", "start_watchdog",
           "stop_watchdog", "check_peer_failure", "monitored_barrier",
           "notify_progress", "current_watchdog", "WATCHDOG_EXIT_CODE"]

WATCHDOG_EXIT_CODE = 117    # distinct from fault.FI_EXIT_CODE and signals


class PeerFailureError(RuntimeError):
    """A peer rank (or the rendezvous store) went stale/dead; carries the
    guilty ranks in .ranks (empty when the store itself vanished).

    `message` MUST stay defaulted: the watchdog's async-raise hands
    PyThreadState_SetAsyncExc the CLASS (per CPython docs), and exception
    normalization in the main thread instantiates it with no arguments —
    a required positional would turn the raise into a bare TypeError and
    the documented `except PeerFailureError` recovery path would never
    match. The detailed cause is always at current_watchdog().failure."""

    def __init__(self, message="peer failure detected — see the watchdog "
                 "log or current_watchdog().failure for the recorded cause",
                 ranks=()):
        super().__init__(message)
        self.ranks = tuple(ranks)


class Watchdog:
    """`store_factory(timeout_s)` must return a connected TCPStore-like
    client, honoring `timeout_s` as its CONNECT timeout — reconnect
    attempts inside the watchdog must stay well under the watchdog
    timeout, or a dead store would stall detection for the full default
    connect-retry window."""

    def __init__(self, store_factory, rank: int, world: int,
                 timeout_s: float = None, interval_s: float = None,
                 action: str = None, kill_grace_s: float = None):
        self._store_factory = store_factory
        self.rank = int(rank)
        self.world = int(world)
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else os.environ.get("PADDLE_WATCHDOG_TIMEOUT_S", "300"))
        self.interval_s = float(
            interval_s if interval_s is not None
            else os.environ.get("PADDLE_HEARTBEAT_INTERVAL_S",
                                str(min(1.0, self.timeout_s / 4))))
        self.action = action or os.environ.get("PADDLE_WATCHDOG_ACTION",
                                               "raise")
        self.kill_grace_s = float(
            kill_grace_s if kill_grace_s is not None
            else os.environ.get("PADDLE_WATCHDOG_KILL_GRACE_S",
                                str(self.timeout_s)))
        self._connect_timeout = min(self.timeout_s, 5.0)
        self.require_progress_s = float(
            os.environ.get("PADDLE_WATCHDOG_REQUIRE_PROGRESS_S", "0"))
        self._progress_at = time.monotonic()
        # telemetry surface (inference/telemetry.py folds these into the
        # Prometheus exposition): per-peer heartbeat freshness + how many
        # peer failures this watchdog has recorded
        self._seen = {}                 # peer -> (counter, t_progress)
        self._done_peers = set()        # peers that departed cleanly
        self._watch_started = time.monotonic()
        self.peer_failures = 0
        self.failure: PeerFailureError | None = None
        self._crashed = False     # set by the excepthook start_watchdog installs
        self._stop = threading.Event()
        self._pub_store = None
        self._watch_store = None
        self._threads = []
        self._main_thread = threading.current_thread()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        for name, fn in (("hb-pub", self._publish_loop),
                         ("hb-watch", self._watch_loop)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"paddle-watchdog-{name}")
            t.start()
            self._threads.append(t)
        return self

    def mark_clean_exit(self):
        """Publish `wd/done/<rank>`: peers exempt this rank from
        staleness — a FINISHED rank stops beating, and that is departure,
        not death. start_watchdog registers this with atexit (after
        jax's own handlers, so it runs before jax's shutdown wait); hard
        failure paths use os._exit and correctly skip it.

        Rank 0 additionally LINGERS (PADDLE_WATCHDOG_DRAIN_S, default 5)
        because the TCPStore daemon rides its process (parallel.py): the
        store must outlive the gang long enough for every survivor's
        watcher to cache this marker — otherwise "coordinator finished
        first" is indistinguishable from "coordinator died". Exits early
        once all peers have posted their own markers."""
        if self._crashed or self.failure is not None:
            # atexit fires on uncaught-exception deaths too; a rank dying
            # of a crash (or exiting because a PEER failed) must stay
            # flaggable — posting done here would exempt a dead rank from
            # staleness and wedge the survivors in their next collective
            return
        log_event("watchdog", "clean_exit", rank=self.rank)
        try:
            s = self._store_factory(self._connect_timeout)
            s.set(f"wd/done/{self.rank}", b"1")
            if self.rank == 0 and self.world > 1:
                drain = float(os.environ.get("PADDLE_WATCHDOG_DRAIN_S",
                                             "5"))
                deadline = time.monotonic() + drain
                while time.monotonic() < deadline:
                    if all(s.get(f"wd/done/{p}") is not None
                           for p in range(self.world) if p != self.rank):
                        break
                    time.sleep(min(0.2, self.interval_s))
            s.close()
        except Exception:
            pass                 # store gone: nobody is left to misjudge us

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        for s in (self._pub_store, self._watch_store):
            try:
                if s is not None:
                    s.close()
            except Exception:
                pass

    def notify_progress(self):
        """Stamp main-thread liveness (called from Optimizer.step). Only
        consulted when PADDLE_WATCHDOG_REQUIRE_PROGRESS_S > 0."""
        self._progress_at = time.monotonic()

    def _progress_stale(self) -> bool:
        return (self.require_progress_s > 0
                and time.monotonic() - self._progress_at
                > self.require_progress_s)

    # ------------------------------------------------------------ publisher
    def _publish_loop(self):
        from ...testing import fault
        while not self._stop.is_set():
            try:
                if not fault.heartbeat_dropped(self.rank) \
                        and not self._progress_stale():
                    if self._pub_store is None:
                        self._pub_store = self._store_factory(
                            self._connect_timeout)
                    self._pub_store.add(f"hb/{self.rank}", 1)
                    # piggyback the flight-recorder snapshot (fr/<rank>)
                    # on the same cadence: cluster_snapshot() aggregates
                    # these exactly like heartbeats
                    flight_recorder.maybe_publish(self._pub_store)
            except Exception:
                # publisher never escalates: liveness judgements belong to
                # the PEERS' watchers; a broken local store just means our
                # counter stalls and they flag us
                self._pub_store = None
            self._stop.wait(self.interval_s)

    # -------------------------------------------------------------- watcher
    def _watch_loop(self):
        seen = self._seen               # peer -> (counter, t_progress)
        done = self._done_peers         # peers that posted wd/done/<rank>
        t0 = self._watch_started = time.monotonic()
        store_ok_at = t0
        while not self._stop.is_set():
            now = time.monotonic()
            stale = []
            try:
                if self._watch_store is None:
                    self._watch_store = self._store_factory(
                        self._connect_timeout)
                # liveness ping FIRST: get() on a dead connection reports
                # "no value" (indistinguishable from a missing key, which
                # would misattribute a dead STORE as stale PEERS), while
                # set() raises — so a broken store routes to the except
                # branch and its own timeout
                self._watch_store.set(f"wd/ping/{self.rank}", b"1")
                for peer in range(self.world):
                    if peer == self.rank or peer in done:
                        continue
                    # clean-exit markers are polled EAGERLY (not only once
                    # stale): they must be cached before the store itself
                    # can die with the departing coordinator — a finished
                    # rank is departure, not death
                    if self._watch_store.get(f"wd/done/{peer}") is not None:
                        done.add(peer)
                        continue
                    v = self._watch_store.get(f"hb/{peer}")
                    count = (int.from_bytes(v[:8], "little", signed=True)
                             if v is not None and len(v) >= 8 else None)
                    prev = seen.get(peer)
                    if count is not None and (prev is None
                                              or count > prev[0]):
                        seen[peer] = (count, now)
                    else:
                        # never-seen peers age from watchdog start — a rank
                        # that dies before its first beat must still be
                        # named, not waited on forever
                        since = prev[1] if prev is not None else t0
                        if now - since > self.timeout_s:
                            stale.append(peer)
                store_ok_at = now
            except Exception as e:
                self._watch_store = None
                # fresh clock: the failed reconnect itself may have eaten
                # most of the budget
                now = time.monotonic()
                if now - store_ok_at > self.timeout_s:
                    if 0 in done or len(done) == self.world - 1:
                        # the store daemon rides rank 0's process: rank 0
                        # departing CLEANLY takes the store with it, and
                        # that is job teardown, not coordinator death —
                        # likewise when every peer already departed. The
                        # watchdog retires (remaining peers, if any, are
                        # unmonitorable without a store anyway).
                        logging.info(
                            "paddle_tpu watchdog: [rank %d] store retired "
                            "with a clean coordinator exit — watchdog "
                            "stopping", self.rank)
                        log_event("watchdog", "store_retired",
                                  rank=self.rank,
                                  peers_departed=sorted(done))
                        return
                    self._fail(PeerFailureError(
                        f"[rank {self.rank}] watchdog: rendezvous store "
                        f"unreachable for >{self.timeout_s:.1f}s ({e!r}) — "
                        "coordinator host presumed dead", ranks=()))
                    return
            if stale:
                self._fail(PeerFailureError(
                    f"[rank {self.rank}] watchdog: no heartbeat from rank"
                    f"{'s' if len(stale) > 1 else ''} "
                    f"{', '.join(map(str, stale))} for "
                    f"> {self.timeout_s:.1f}s (PADDLE_WATCHDOG_TIMEOUT_S) "
                    "— peer presumed hung or dead", ranks=stale))
                return
            self._stop.wait(min(self.interval_s, self.timeout_s / 4))

    # -------------------------------------------------------------- failure
    def _fail(self, err: PeerFailureError):
        self.failure = err
        self.peer_failures += 1
        # flight dump FIRST (best-effort, never blocks failure handling):
        # the recorder tail + all-thread stacks at the moment of
        # detection are what the supervisor's cross-rank diagnosis needs,
        # and the hard-exit path below never returns
        flight_recorder.dump_on_failure("peer_failure")
        logging.error("paddle_tpu watchdog: %s", err)
        log_event("watchdog", "peer_failure",
                  message=f"paddle_tpu watchdog: {err}",
                  rank=self.rank, ranks=list(err.ranks),
                  timeout_s=self.timeout_s, action=self.action)
        if self.action != "raise":
            return
        # async-raise into the main thread: a Python-level train loop dies
        # at its next bytecode boundary with the real exception
        tid = self._main_thread.ident
        if tid is not None:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(PeerFailureError))
        # backstop for ranks wedged inside a C-level collective (no
        # bytecode ever runs again): bounded grace, then hard exit so the
        # gang supervisor can tear down and restart promptly
        deadline = time.monotonic() + self.kill_grace_s
        while time.monotonic() < deadline:
            if self._stop.wait(0.2):
                return               # main thread handled it and stopped us
        log_event("watchdog", "hard_exit",
                  message=f"paddle_tpu watchdog: [rank {self.rank}] main "
                          f"thread did not unwind within "
                          f"{self.kill_grace_s:.1f}s grace — hard-exiting "
                          f"{WATCHDOG_EXIT_CODE}",
                  rank=self.rank, exit_code=WATCHDOG_EXIT_CODE)
        os._exit(WATCHDOG_EXIT_CODE)

    def check(self):
        if self.failure is not None:
            raise self.failure

    # ------------------------------------------------------------- gauges
    def heartbeat_ages(self):
        """Seconds since each peer's heartbeat counter last PROGRESSED
        (a peer that never beat ages from watchdog start — the same
        staleness clock the watcher judges by, so a gauge crossing
        ``timeout_s`` is exactly a pending PeerFailureError). Peers
        that posted clean-exit markers are omitted: departure, not
        death."""
        now = time.monotonic()
        ages = {}
        for peer in range(self.world):
            if peer == self.rank or peer in self._done_peers:
                continue
            rec = self._seen.get(peer)
            ages[peer] = now - (rec[1] if rec is not None
                                else self._watch_started)
        return ages

    def gauges(self):
        """Telemetry surface (folded into the Prometheus exposition by
        inference/telemetry.runtime_prometheus)."""
        return {"rank": self.rank, "world": self.world,
                "timeout_s": self.timeout_s,
                "peer_failures_total": self.peer_failures,
                "heartbeat_age_s": self.heartbeat_ages()}

    # ------------------------------------------------------------- barrier
    def monitored_barrier(self, timeout_s: float = None, tag: str = None):
        """Store-backed barrier that NAMES the ranks that never arrived
        (reference: ProcessGroup::monitoredBarrier). Two phases: every
        rank posts an arrival key, rank 0 waits for all then posts the
        release; a timeout raises PeerFailureError listing the absentees
        instead of wedging."""
        timeout_s = float(timeout_s if timeout_s is not None
                          else self.timeout_s)
        with flight_recorder.record_span("monitored_barrier",
                                         group="world", note=tag):
            self._monitored_barrier_inner(timeout_s, tag)

    def _monitored_barrier_inner(self, timeout_s, tag):
        store = self._store_factory(min(timeout_s, 5.0))
        try:
            if tag is not None:
                # caller-chosen tags must be unique per store lifetime
                seq = tag
            else:
                # the per-rank call counter lives in the STORE, not the
                # instance: a stop_watchdog()/start_watchdog() cycle
                # against the same store daemon must not restart at seq 1
                # and match a previous generation's stale mb/ keys
                seq = str(store.add(f"mb/cnt/{self.rank}", 1))
            store.set(f"mb/{seq}/{self.rank}", b"1")
            deadline = time.monotonic() + timeout_s
            if self.rank == 0:
                missing = [r for r in range(1, self.world)]
                while missing and time.monotonic() < deadline:
                    self.check()
                    missing = [r for r in missing
                               if store.get(f"mb/{seq}/{r}") is None]
                    if missing:
                        time.sleep(0.05)
                if missing:
                    raise PeerFailureError(
                        f"monitored_barrier({seq!r}): rank"
                        f"{'s' if len(missing) > 1 else ''} "
                        f"{', '.join(map(str, missing))} did not arrive "
                        f"within {timeout_s:.1f}s", ranks=missing)
                store.set(f"mb/{seq}/go", b"1")
            else:
                while store.get(f"mb/{seq}/go") is None:
                    self.check()
                    if time.monotonic() > deadline:
                        raise PeerFailureError(
                            f"monitored_barrier({seq!r}): rank 0 did not "
                            f"release within {timeout_s:.1f}s (it, or a "
                            "rank it waits on, is gone)", ranks=(0,))
                    time.sleep(0.05)
        finally:
            try:
                store.close()
            except Exception:
                pass


# ------------------------------------------------------------------ module
_watchdog: list = [None]


def current_watchdog() -> Watchdog | None:
    return _watchdog[0]


def start_watchdog(store_factory, rank: int, world: int, **kw) -> Watchdog:
    """Install + start the process-global watchdog (idempotent)."""
    if _watchdog[0] is not None:
        return _watchdog[0]
    wd = Watchdog(store_factory, rank, world, **kw).start()
    _watchdog[0] = wd
    # LIFO atexit: registered after jax's import-time handlers, so the
    # clean-exit marker lands BEFORE jax's shutdown (which can wedge on a
    # dead peer) — a rank exiting 0 must not read as a peer failure
    import atexit
    atexit.register(wd.mark_clean_exit)
    # atexit cannot tell "finished" from "died of an uncaught exception";
    # flag crashes so mark_clean_exit refuses to exempt a dead rank
    import sys
    prev_hook = sys.excepthook

    def _crash_hook(tp, val, tb):
        wd._crashed = True
        prev_hook(tp, val, tb)

    sys.excepthook = _crash_hook
    return wd


def stop_watchdog():
    if _watchdog[0] is not None:
        _watchdog[0].stop()
        import atexit
        # drop the clean-exit hook with the watchdog: a start/stop cycle
        # must not leave stale callbacks that reconnect (or, for rank 0,
        # drain) against a later generation's store at interpreter exit
        try:
            atexit.unregister(_watchdog[0].mark_clean_exit)
        except Exception:
            pass
        _watchdog[0] = None


def check_peer_failure():
    """Raise the recorded PeerFailureError, if any. Hooked into the
    train-step path (Optimizer.step) and callable from any host-side
    control point; ~one attribute load when healthy."""
    wd = _watchdog[0]
    if wd is not None and wd.failure is not None:
        raise wd.failure


def notify_progress():
    """Stamp main-thread liveness on the global watchdog (no-op when no
    watchdog is running). See PADDLE_WATCHDOG_REQUIRE_PROGRESS_S."""
    wd = _watchdog[0]
    if wd is not None:
        wd.notify_progress()


def monitored_barrier(timeout_s: float = None, tag: str = None):
    """Module-level convenience over the global watchdog's barrier.

    Single-process: trivially satisfied. Multi-process WITHOUT a running
    watchdog raises instead of silently skipping — callers rely on this
    for ordering (e.g. "all ranks wrote before rank 0 reads"), and a
    no-op here would be a data race the caller can't detect."""
    wd = _watchdog[0]
    if wd is not None:
        wd.monitored_barrier(timeout_s=timeout_s, tag=tag)
        return
    try:
        from ..parallel import get_world_size
        world = get_world_size()
    except Exception:
        world = 1
    try:
        # a launched-but-uninitialized rank only has the env contract
        world = max(world, int(os.environ.get("PADDLE_TRAINERS_NUM") or 1))
    except ValueError:
        pass
    if world > 1:
        raise RuntimeError(
            f"monitored_barrier() in a {world}-process job but no watchdog "
            "is running (it failed to start, or PADDLE_WATCHDOG_TIMEOUT_S=0"
            " disabled it) — refusing to silently skip a synchronization "
            "point; use init_parallel_env()'s store barrier or re-enable "
            "the watchdog")
