"""paddle.distributed.sharding user API. Parity:
python/paddle/distributed/sharding/group_sharded.py ::
group_sharded_parallel(level="os"/"os_g"/"p_g_os") / save_group_sharded_model.
"""
from __future__ import annotations

from ..fleet.meta_parallel.sharding.group_sharded import (
    GroupShardedStage2, GroupShardedStage3, GroupShardedOptimizerStage2,
    DygraphShardingOptimizer)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    assert level in ("os", "os_g", "p_g_os"), f"bad sharding level {level}"
    if offload:
        # loud at every level — a ported reference offload config must not
        # silently lose the behavior (stage wrappers also raise; this
        # covers level="os", whose optimizer wrapper has no offload knob)
        raise NotImplementedError(
            "group_sharded_parallel(offload=True): CPU offload is not "
            "implemented on the TPU backend (sharded state is HBM-resident)")
    if level == "os":
        opt = DygraphShardingOptimizer(optimizer)
        return model, opt, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(model.parameters(), optimizer,
                                          group=group, offload=offload)
        wrapped = GroupShardedStage2(model, opt, group=group,
                                     sync_buffers=sync_buffers,
                                     buffer_max_size=buffer_max_size)
        return wrapped, opt, scaler
    wrapped = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                 sync_buffers=sync_buffers,
                                 segment_size=segment_size, offload=offload,
                                 sync_comm=sync_comm, dp_group=dp_group,
                                 exclude_layer=exclude_layer)
    return wrapped, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..fleet.meta_parallel.sharding.group_sharded import GroupShardedStage3
    from ...framework.io import save
    os.makedirs(output, exist_ok=True)
    target = model
    if isinstance(model, GroupShardedStage3):
        model.get_all_parameters()
        target = model._layers
    save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
