"""Distributed bootstrap. Parity: python/paddle/distributed/parallel.py ::
init_parallel_env + ParallelEnv.

Reference flow: parse PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS env, TCPStore
rendezvous, create default ProcessGroupNCCL. TPU-native flow: the JAX
coordination service replaces TCPStore (jax.distributed.initialize), and the
"default process group" is the global device mesh — collectives are XLA ops
over ICI/DCN, not NCCL rings.

Rank semantics on a single-controller SPMD runtime:
  * host-side code (data loading, logging, checkpoint IO) sees
    process-level rank/world (one process per host);
  * per-chip rank differences live INSIDE compiled programs (mesh
    coordinates), not in Python control flow.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from .resilience.flight_recorder import instrumented as _fr_instrumented

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "all_reduce_gradients", "is_initialized_env"]

_state = {"initialized": False, "rank": 0, "world_size": 1, "mesh": None}


def _maybe_start_watchdog(rank: int, world: int):
    """Start the heartbeat watchdog (resilience/watchdog.py) over the same
    TCPStore daemon _store_barrier runs one port above the coordinator.
    Multi-process only; PADDLE_WATCHDOG_TIMEOUT_S=0 disables; best-effort
    when the native runtime is unavailable."""
    if world <= 1:
        return
    if float(os.environ.get("PADDLE_WATCHDOG_TIMEOUT_S", "300")) <= 0:
        return
    coord = os.environ.get("PADDLE_MASTER",
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if not coord:
        return
    try:
        from ..core.native import TCPStore, load_native
        if load_native() is None:
            return
    except Exception:
        return
    host, port = coord.rsplit(":", 1)
    store_port = int(port) + 1
    connect_t = float(os.environ.get("PADDLE_STORE_CONNECT_TIMEOUT", "15"))

    def factory(timeout_s=None):
        return TCPStore(host, store_port,
                        timeout_s=connect_t if timeout_s is None
                        else timeout_s)

    try:  # one SHORT probe connection: no store daemon -> no watchdog
        # (full connect_t here would stall init when the rendezvous store
        # was skipped, e.g. its port was taken)
        TCPStore(host, store_port, timeout_s=min(connect_t, 2.0)).close()
    except Exception:
        import logging
        logging.warning("paddle_tpu: heartbeat watchdog disabled (store "
                        "%s:%d unreachable)", host, store_port)
        return
    from .resilience import start_watchdog
    start_watchdog(factory, rank, world)


def _maybe_jax_distributed_init():
    """Multi-host init from PADDLE_* or JAX_* env (TCPStore-equivalent)."""
    n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                           os.environ.get("JAX_NUM_PROCESSES", "1")))
    if n <= 1:
        return
    # must NOT call jax.process_count() here: it initializes the XLA
    # backend, after which jax.distributed.initialize refuses to run —
    # probe the distributed client state instead
    try:
        from jax._src import distributed as _jd
        if getattr(_jd.global_state, "client", None) is not None:
            return
    except Exception:
        pass
    coord = os.environ.get("PADDLE_MASTER",
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("JAX_PROCESS_ID", "0")))
    try:
        # jax < 0.5 leaves CPU collectives on the XLA default, which
        # raises "Multiprocess computations aren't implemented on the
        # CPU backend" at the first cross-process op; newer jax defaults
        # to gloo and drops the flag (hence best-effort). Must be set
        # BEFORE the backend client is created — i.e. right here, ahead
        # of jax.distributed.initialize.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    if coord:
        _store_barrier(coord, n, pid)
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=n, process_id=pid)
        except RuntimeError:
            # already initialized (user called it, or the private-state
            # probe above failed on a newer jax) — proceed with the
            # existing client
            if jax.process_count() != n:
                raise


def _store_barrier(coord: str, world: int, rank: int):
    """Pre-init rendezvous over the native TCPStore (csrc/runtime.cc —
    parity: paddle/fluid/distributed/store/tcp_store.cc): rank 0 runs the
    master daemon one port above the coordinator port, every rank registers
    and waits until all are present, so jax.distributed.initialize never
    races a late-starting coordinator. Best-effort: skipped when the native
    runtime is unavailable."""
    try:
        from ..core.native import TCPStore, TCPStoreServer
    except Exception:
        return
    import logging
    try:
        host, port = coord.rsplit(":", 1)
        store_port = int(port) + 1
        if rank == 0:
            try:
                srv = TCPStoreServer(store_port)
                _state["_store_server"] = srv   # keep alive for the job
            except OSError as e:
                logging.warning(
                    "paddle_tpu: TCPStore barrier master failed to bind "
                    "port %d (%s); skipping pre-init rendezvous", store_port,
                    e)
                return
        # bounded connect: if the master never comes up, fall through to
        # jax.distributed.initialize (which has its own retry) instead of
        # stalling the job for the full store timeout
        c = TCPStore(host, store_port,
                     timeout_s=float(os.environ.get(
                         "PADDLE_STORE_CONNECT_TIMEOUT", "15")))
        c.add("init/count", 1)
        if rank == 0:
            # BOUNDED wait: a peer whose store connect failed skips the
            # rendezvous entirely (best-effort contract), so an open
            # wait here would deadlock the whole job — rank 0 stuck in
            # this loop never reaches jax.distributed.initialize, and
            # every other rank then blocks inside it forever. On
            # timeout, release any ranks that DID register and fall
            # through to jax.distributed.initialize, which is the real
            # (coordinator-side) rendezvous anyway.
            import time
            deadline = time.time() + float(os.environ.get(
                "PADDLE_STORE_CONNECT_TIMEOUT", "15"))
            while c.get("init/count") is None or \
                    int.from_bytes(c.get("init/count")[:8], "little",
                                   signed=True) < world:
                if time.time() > deadline:
                    logging.warning(
                        "paddle_tpu: TCPStore pre-init rendezvous timed "
                        "out with %s/%d ranks registered; proceeding",
                        c.get("init/count") and int.from_bytes(
                            c.get("init/count")[:8], "little",
                            signed=True), world)
                    break
                time.sleep(0.05)
            c.set("init/ready", b"1")
        c.wait("init/ready", timeout_s=float(os.environ.get(
            "PADDLE_STORE_TIMEOUT", "300")))
        c.close()
    except Exception as e:
        logging.warning("paddle_tpu: TCPStore pre-init rendezvous skipped "
                        "(%s)", e)


def init_parallel_env():
    if _state["initialized"]:
        return ParallelEnv()
    _maybe_jax_distributed_init()
    _state["rank"] = jax.process_index()
    _state["world_size"] = jax.process_count()
    _state["initialized"] = True
    from ..testing import fault
    fault.inject("init", rank=_state["rank"])
    # flight recorder: authoritative rank/world (default on at world>1),
    # and a SIGTERM dump hook so a rank the supervisor reaps leaves its
    # collective timeline behind for the cross-rank diagnosis
    from .resilience import flight_recorder
    flight_recorder.configure(rank=_state["rank"],
                              world=_state["world_size"])
    if _state["world_size"] > 1:
        flight_recorder.install_signal_dump()
    _maybe_start_watchdog(_state["rank"], _state["world_size"])
    from .communication.group import _ensure_default_group
    _ensure_default_group()
    return ParallelEnv()


def is_initialized_env() -> bool:
    return _state["initialized"]


def get_rank(group=None) -> int:
    if group is not None:
        from .communication.group import Group
        if isinstance(group, Group):
            return group.get_group_rank(_state["rank"])
    return _state["rank"] if _state["initialized"] else jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        from .communication.group import Group
        if isinstance(group, Group):
            return group.nranks
    return _state["world_size"] if _state["initialized"] else jax.process_count()


class ParallelEnv:
    """Parity: python/paddle/distributed/parallel.py :: ParallelEnv."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def dev_id(self) -> int:
        return self.local_rank

    @property
    def device_type(self) -> str:
        d = jax.devices()[0].platform
        return "tpu" if d in ("tpu", "axon") else d

    @property
    def trainer_endpoints(self) -> list:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")


@_fr_instrumented("all_reduce_gradients")
def all_reduce_gradients(params, group=None):
    """DataParallel grad sync: mean-allreduce every .grad across dp ranks.

    Parity: EagerReducer's bucketed allreduce
    (paddle/fluid/distributed/collective/reducer.cc). Under XLA one fused
    program per step IS the bucket fusion; eagerly this is a no-op at
    world_size 1 and a psum at >1. Recorded as ONE logical collective in
    the flight recorder (the per-param all_reduce calls nest under it).
    """
    ws = get_world_size(group)
    if ws <= 1:
        return
    from .resilience import check_peer_failure
    check_peer_failure()   # fail fast instead of entering a doomed psum
    from .communication.all_reduce import all_reduce
    from ..tensor.tensor import no_grad
    with no_grad():
        for p in params:
            if p.grad is not None:
                all_reduce(p.grad, group=group)
                p.grad._data = p.grad._data / ws
