"""paddle.distributed.rpc — remote procedure calls between workers.

Parity: python/paddle/distributed/rpc/ :: init_rpc, rpc_sync, rpc_async,
shutdown, get_worker_info (the reference backs this with brpc; here the
transport is the framework's own C++ TCPStore rendezvous + a per-worker
TCP listener thread, keeping the runtime native where the reference's is).

Security note (same contract as the reference): payloads are pickled —
RPC peers are trusted cluster members, never untrusted input. As a
defense-in-depth layer a random session token is minted at rendezvous
(rank 0 → store) and required as a message preamble BEFORE anything is
unpickled, so network reach to the listener alone is not enough to
execute code; reach to the rendezvous store is required."""
from __future__ import annotations

import hmac
import os
import pickle
import secrets
import socket
import struct
import threading
import time
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "ping",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


def _telemetry():
    """The runtime metrics registry (inference/telemetry.py — import-
    light, lazy: rpc must not pay for it until the first call). Returns
    None when unavailable so the transport never fails on metrics."""
    global _TELE
    if _TELE is None:
        try:
            from ..inference import telemetry as _t
            _TELE = _t
        except Exception:
            _TELE = False
    return _TELE or None


_TELE = None


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _FutureResult:
    """Minimal future for rpc_async (reference returns a FutureWrapper)."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def _set(self, value=None, exc=None):
        self._value, self._exc = value, exc
        self._event.set()

    def wait(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("rpc future timed out")
        if self._exc is not None:
            raise self._exc
        return self._value

    def done(self):
        return self._event.is_set()


class _RpcAgent:
    def __init__(self, name, rank, world_size, store):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind the advertised interface, not 0.0.0.0, so the listener is
        # only reachable on the address peers are told about
        self._ip = self._advertised_ip()
        try:
            self._server.bind((self._ip, 0))
        except OSError:
            self._server.bind(("0.0.0.0", 0))
        self._server.listen(64)
        self.port = self._server.getsockname()[1]
        self._token = b""  # minted/fetched at rendezvous (start())
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self.workers: dict[str, WorkerInfo] = {}

    def start(self):
        """Serve + rendezvous. Called AFTER the module-global agent slot is
        assigned: a peer may invoke a remote fn that itself calls
        get_worker_info() the instant our endpoint is published, so
        publishing before the slot is set races."""
        # session token: rank 0 mints, everyone fetches via the store —
        # possession proves rendezvous membership and gates unpickling
        if self.rank == 0:
            self._token = secrets.token_bytes(32)
            self.store.set("rpc/token", self._token.hex().encode())
        else:
            self._token = bytes.fromhex(
                self._store_get_blocking("rpc/token").decode())
        self._thread.start()
        self.store.set(f"rpc/{self.rank}",
                       f"{self.name}|{self._ip}|{self.port}".encode())
        for r in range(self.world_size):
            raw = self._store_get_blocking(f"rpc/{r}")
            n, ip, port = raw.decode().split("|")
            self.workers[n] = WorkerInfo(n, r, ip, int(port))

    @staticmethod
    def _advertised_ip() -> str:
        """Peer-reachable address: explicit env wins (the launcher sets it
        multi-host), else the hostname's IP, else loopback (single-host)."""
        my_ip = os.environ.get("PADDLE_CURRENT_ENDPOINT", "").rsplit(
            ":", 1)[0] or os.environ.get("POD_IP", "")
        if not my_ip:
            try:
                my_ip = socket.gethostbyname(socket.gethostname())
            except OSError:
                my_ip = "127.0.0.1"
        return my_ip

    def _store_get_blocking(self, key, timeout=60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                v = self.store.get(key)
                if v:
                    return v
            except Exception:
                pass
            time.sleep(0.05)
        raise TimeoutError(f"rpc rendezvous: key {key} never appeared")

    # --------------------------------------------------------- transport
    @staticmethod
    def _send_msg(sock, payload: bytes):
        sock.sendall(struct.pack("<Q", len(payload)) + payload)

    @staticmethod
    def _recv_msg(sock, deadline=None) -> bytes:
        # the deadline bounds the WHOLE message, re-armed before every
        # recv — a per-op timeout alone lets a peer dripping one byte per
        # interval hold the caller far past the advertised call deadline
        def _read(nbytes):
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("rpc recv: call deadline exceeded "
                                       "mid-message")
                sock.settimeout(left)
            return sock.recv(nbytes)

        hdr = b""
        while len(hdr) < 8:
            chunk = _read(8 - len(hdr))
            if not chunk:
                raise ConnectionError("rpc peer closed")
            hdr += chunk
        (n,) = struct.unpack("<Q", hdr)
        buf = bytearray()
        while len(buf) < n:
            chunk = _read(min(1 << 20, n - len(buf)))
            if not chunk:
                raise ConnectionError("rpc peer closed mid-message")
            buf += chunk
        return bytes(buf)

    def _serve(self):
        while not self._stop:
            try:
                self._server.settimeout(0.2)
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            msg = self._recv_msg(conn)
            # constant-time token check BEFORE unpickling anything
            if len(msg) < 32 or not hmac.compare_digest(msg[:32],
                                                        self._token):
                return
            fn, args, kwargs = pickle.loads(msg[32:])
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # ship the exception back
                result = (False, e)
            try:
                payload = pickle.dumps(result)
            except Exception as e:  # unpicklable result/exception
                payload = pickle.dumps(
                    (False, RuntimeError(
                        f"rpc result not picklable: {e!r}; "
                        f"result/exception was {result[1]!r}")))
            self._send_msg(conn, payload)
        except Exception:
            pass
        finally:
            conn.close()

    def call(self, to: str, fn, args, kwargs, timeout):
        """One bounded RPC round-trip. The connect is RETRIED with
        exponential backoff inside the call deadline (a peer mid-restart
        refuses for a moment — that's recoverable); once connected, every
        socket op inherits the remaining deadline, so a half-open peer
        turns into TimeoutError instead of an unbounded wait. The whole
        round-trip rides the flight-recorder choke point (kind "rpc"),
        so a dump taken while a call is outstanding shows which peer it
        was waiting on."""
        from .resilience import flight_recorder
        info = self.workers[to]
        t_call = time.monotonic()
        try:
            with flight_recorder.record_span(
                    "rpc", kind="rpc", group=f"rpc:{to}",
                    note=getattr(fn, "__name__", str(fn))):
                ok, value = self._call_inner(info, to, fn, args, kwargs,
                                             timeout)
        except Exception:
            # transport failure: counted, NOT recorded in the latency
            # histogram (a timed-out call's "latency" is the deadline)
            tele = _telemetry()
            if tele is not None:
                tele.runtime_counter("paddle_rpc_calls_total", 1)
                tele.runtime_counter("paddle_rpc_call_errors_total", 1)
            raise
        tele = _telemetry()
        if tele is not None:
            # a remote exception shipped back IS a completed round-trip
            tele.runtime_counter("paddle_rpc_calls_total", 1)
            tele.runtime_histogram(
                "paddle_rpc_call_latency_seconds").observe(
                time.monotonic() - t_call)
        if not ok:
            raise value
        return value

    def _call_inner(self, info, to, fn, args, kwargs, timeout):
        # flaky-transport fault injection (PADDLE_FI_RPC_DELAY_MS /
        # PADDLE_FI_RPC_ERR_RATE): fires BEFORE the wire so an injected
        # error is indistinguishable from a connect failure to callers
        from ..testing import fault
        fault.rpc_flaky()
        deadline = time.monotonic() + timeout
        # deadline-bounded by default: a refused connect is instantaneous,
        # and a peer mid-restart stays refused for the supervisor's whole
        # backoff window — counting attempts would burn <1s of a 30s
        # budget. PADDLE_RPC_CONNECT_RETRIES>0 adds an attempt cap on top.
        retries = int(os.environ.get("PADDLE_RPC_CONNECT_RETRIES", "0"))
        backoff = float(os.environ.get("PADDLE_RPC_CONNECT_BACKOFF_S",
                                       "0.1"))
        sock, last, attempt = None, None, 0
        while sock is None:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"rpc to {to!r} ({info.ip}:{info.port}): connect "
                    f"deadline exceeded ({timeout}s, {attempt} attempts; "
                    f"last error: {last!r})")
            try:
                sock = socket.create_connection((info.ip, info.port),
                                                timeout=left)
            except OSError as e:
                last = e
                attempt += 1
                if retries > 0 and attempt >= retries:
                    raise ConnectionError(
                        f"rpc to {to!r} ({info.ip}:{info.port}): connect "
                        f"failed after {attempt} attempts: {last!r}")
                time.sleep(min(backoff * (2 ** (attempt - 1)), 5.0,
                               max(0.0, deadline - time.monotonic())))
        with sock:
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            self._send_msg(sock, self._token + pickle.dumps(
                (fn, args or (), kwargs or {})))
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            ok, value = pickle.loads(self._recv_msg(sock, deadline))
        return ok, value

    def stop(self):
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


_agent: list = [None]


def init_rpc(name: str, rank: int = None, world_size: int = None,
             master_endpoint: str = None):
    """Start this worker's RPC agent. Env fallbacks mirror the reference:
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER."""
    from ..core.native import TCPStore, TCPStoreServer

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    ep = master_endpoint or os.environ.get("PADDLE_MASTER",
                                           "127.0.0.1:0")
    host, port = ep.rsplit(":", 1)
    server = None
    if rank == 0:
        # port 0 (ephemeral) only works when all agents share this
        # process (tests); multi-process jobs must fix the port
        server = TCPStoreServer(int(port))
        port = server.port
    store = TCPStore(host, int(port))
    agent = _RpcAgent(name, rank, world_size, store)
    agent._store_server = server
    _agent[0] = agent
    agent.start()
    return agent


def _require_agent() -> _RpcAgent:
    if _agent[0] is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _agent[0]


def _resolve_timeout(timeout):
    """None -> the env-configurable default (PADDLE_RPC_TIMEOUT_S, 30 s).
    There is deliberately NO infinite mode: a half-open peer must become
    a timely TimeoutError, never a forever-hung caller."""
    if timeout is None:
        return float(os.environ.get("PADDLE_RPC_TIMEOUT_S", "30"))
    return float(timeout)


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=None):
    """Run fn(*args, **kwargs) on worker `to`; block for the result."""
    return _require_agent().call(to, fn, args, kwargs,
                                 _resolve_timeout(timeout))


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=None):
    """Like rpc_sync but returns a future with .wait()."""
    agent = _require_agent()
    timeout = _resolve_timeout(timeout)
    fut = _FutureResult()

    def run():
        try:
            fut._set(value=agent.call(to, fn, args, kwargs, timeout))
        except Exception as e:
            fut._set(exc=e)
    threading.Thread(target=run, daemon=True).start()
    return fut


def _pong():
    return "pong"


def ping(to: str, timeout=None) -> float:
    """Bounded liveness probe: one trivial round-trip to worker ``to``;
    returns the measured latency in seconds. Raises the usual transport
    errors (TimeoutError / ConnectionError) when the peer is gone — the
    cluster router's replica heartbeat rides exactly this, with a SHORT
    timeout so a dead replica is detected in heartbeats, not in a
    30s-default user-facing call. The probe deadline is tunable
    independently of the call deadline: None falls back to
    PADDLE_RPC_PING_TIMEOUT_S first, then the PADDLE_RPC_TIMEOUT_S
    chain — a 30s liveness probe would hold a health sweep hostage."""
    if timeout is None:
        env = os.environ.get("PADDLE_RPC_PING_TIMEOUT_S")
        if env not in (None, ""):
            timeout = float(env)
    t0 = time.monotonic()
    out = _require_agent().call(to, _pong, (), {},
                                _resolve_timeout(timeout))
    if out != "pong":
        raise ConnectionError(f"rpc ping to {to!r}: bad reply {out!r}")
    return time.monotonic() - t0


def get_worker_info(name: str = None) -> WorkerInfo:
    agent = _require_agent()
    if name is None:
        name = agent.name
    return agent.workers[name]


def get_all_worker_infos():
    return list(_require_agent().workers.values())


def shutdown():
    if _agent[0] is not None:
        agent = _agent[0]
        agent.stop()
        server = getattr(agent, "_store_server", None)
        if server is not None:
            server.stop()
        _agent[0] = None
