"""Semi-auto parallel API. Parity: python/paddle/distributed/auto_parallel/
(ProcessMesh, shard_tensor, shard_op; C++ DistAttr + spmd_rules).

TPU-native: ProcessMesh wraps jax.sharding.Mesh; shard_tensor attaches a
PartitionSpec and (on real multi-device) device_puts the array with a
NamedSharding so GSPMD propagates the placement — the SPMD-rule engine the
reference implements by hand IS XLA's sharding propagation here.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "get_mesh", "set_mesh",
           "dtensor_from_fn", "reshard"]

_global_mesh: list = [None]


class ProcessMesh:
    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        self._jax_mesh = None

    @property
    def ndim(self):
        return len(self.shape)

    def get_dim_size(self, name):
        return self.shape[self.dim_names.index(name)]

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = np.asarray(jax.devices())
            n = int(np.prod(self.shape))
            if devs.size < n:
                reps = -(-n // devs.size)
                devs = np.tile(devs, reps)
            self._jax_mesh = Mesh(devs[:n].reshape(self.shape),
                                  tuple(self.dim_names))
        return self._jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def set_mesh(mesh: ProcessMesh):
    _global_mesh[0] = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh[0]


def _placements_to_spec(placements, mesh: ProcessMesh, ndim: int):
    """placements: list like [Shard(0), Replicate()] per mesh dim → P spec."""
    spec = [None] * ndim
    for dim_idx, pl in enumerate(placements or []):
        if hasattr(pl, "get_dim"):
            spec[pl.get_dim()] = mesh.dim_names[dim_idx]
        elif isinstance(pl, str) and pl.startswith("shard:"):
            spec[int(pl.split(":")[1])] = mesh.dim_names[dim_idx]
    return P(*spec)


class Shard:
    def __init__(self, dim):
        self.dim = dim

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim


class Replicate:
    def is_replicate(self):
        return True


class Partial:
    def is_partial(self):
        return True


__all__ += ["Shard", "Replicate", "Partial"]


# Placement-generation counter: every (re)annotation bumps it, and the
# Engine folds it into its conflict-plan cache key — a plan computed
# against one set of parameter placements must not outlive them
# (advisor r4: a stale plan left a NEW conflict unrepaired forever).
_placement_gen = [0]


def bump_placement_generation():
    _placement_gen[0] += 1


def placement_generation() -> int:
    return _placement_gen[0]


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    t = data if isinstance(data, Tensor) else Tensor(np.asarray(data))
    spec = _placements_to_spec(placements, mesh, t.ndim)
    bump_placement_generation()
    t.sharding_spec = spec if not isinstance(t, Tensor) else spec
    try:
        t.split_axis = None
        t.sharding_spec = spec
    except AttributeError:
        pass
    jm = mesh.jax_mesh()
    if len(jax.devices()) >= int(np.prod(mesh.shape)):
        try:
            t._data = jax.device_put(t._data, NamedSharding(jm, spec))
        except Exception:
            pass
    return t


# reshard pass bookkeeping: every reshard appends {shape, from, to,
# bytes_moved} — the cost model the reference's reshard/cost_model.py
# computes per-op; here a per-tensor estimate (full-buffer upper bound
# when placements differ, 0 when they already match). Ring-buffered so a
# reshard-per-step training loop cannot grow memory without bound.
from collections import deque
_reshard_log: "deque" = deque(maxlen=1000)


def reshard_cost_log():
    return list(_reshard_log)


__all__ += ["reshard_cost_log", "clear_reshard_cost_log"]


def clear_reshard_cost_log():
    _reshard_log.clear()


def _reshard_array(arr, jm, spec):
    """Move a raw array to NamedSharding(jm, spec), tolerating mis-sharded
    and cross-mesh inputs (host round-trip fallback). Returns
    (array, bytes_moved_estimate)."""
    target = NamedSharding(jm, spec)
    cur = getattr(arr, "sharding", None)
    try:
        if cur is not None and cur.is_equivalent_to(target, np.ndim(arr)):
            return arr, 0
    except Exception:
        pass
    moved = int(getattr(arr, "nbytes", 0))
    try:
        out = jax.device_put(arr, target)
    except Exception:
        # cross-mesh / incompatible source placement: host round-trip is
        # the universal reshard (the reference's send/recv reshard path)
        out = jax.device_put(np.asarray(arr), target)
    return out, moved


def reshard(tensor, mesh: ProcessMesh, placements):
    """The reshard pass (reference: auto_parallel/static/reshard.py ::
    Resharder): move `tensor` to `placements` on `mesh`, accepting inputs
    that are mis-sharded or live on a different mesh, and log a
    bytes-moved estimate to the cost log."""
    t = tensor if isinstance(tensor, Tensor) else Tensor(np.asarray(tensor))
    spec = _placements_to_spec(placements, mesh, t.ndim)
    jm = mesh.jax_mesh()
    from_desc = str(getattr(getattr(t._data, "sharding", None), "spec",
                            "host/unknown"))
    from ...parallel import _valid_spec
    if not _valid_spec(t._data, spec, jm):
        # indivisible placement: degrade to unsharded rather than raise —
        # the same tolerance every other placement path has
        _reshard_log.append({"shape": tuple(t.shape), "from": from_desc,
                             "to": str(spec), "bytes_moved": 0,
                             "skipped": "indivisible"})
        return t
    if len(jax.devices()) >= int(np.prod(mesh.shape)):
        t._data, moved = _reshard_array(t._data, jm, spec)
    else:
        moved = 0
    t.sharding_spec = spec
    bump_placement_generation()
    _reshard_log.append({"shape": tuple(t.shape), "from": from_desc,
                         "to": str(spec), "bytes_moved": moved})
    return t


def shard_op(op_fn, mesh: ProcessMesh = None, in_shardings=None,
             out_shardings=None):
    def wrapper(*args, **kwargs):
        return op_fn(*args, **kwargs)
    return wrapper


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)
