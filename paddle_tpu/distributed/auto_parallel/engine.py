"""Auto-parallel Engine. Parity: python/paddle/distributed/auto_parallel/
:: Engine (fit/evaluate/predict over a ProcessMesh with annotated
shardings; the reference's planner/partitioner/reshard passes).

TPU-native: there is no program-rewrite pipeline to run — the "planner" is
GSPMD. Engine compiles the train step with jit.to_static over the global
ProcessMesh; `shard_tensor` annotations on parameters become their
placements, the batch is sharded over the mesh's data axis, and XLA's
sharding propagation derives every intermediate placement + collective
(the spmd_rules/ and reshard/ machinery of the reference)."""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor, no_grad
from .api import ProcessMesh, get_mesh

__all__ = ["Engine"]


class _History:
    def __init__(self):
        self.history = {"loss": []}


class Engine:
    """engine = Engine(model, loss_fn, optimizer); engine.fit(dataset)."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss_fn = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else (
            [metrics] if metrics is not None else [])
        self.strategy = strategy
        self._step_fn = None
        self._eval_fn = None
        self._placed = False
        self._reshard_log: list = []
        self._conflict_plan: dict = {}

    @property
    def reshard_cost_log(self):
        """THIS engine's reshard records {shape, from, to, bytes_moved} —
        the placement-aware cost accounting of the planner (per-instance;
        the module-level api.reshard_cost_log() holds public reshard()
        calls)."""
        return list(self._reshard_log)

    # ------------------------------------------------------------ internals
    def _mesh(self):
        pm = get_mesh()
        return pm.jax_mesh() if pm is not None else None

    def _data_axis(self, mesh):
        names = list(mesh.axis_names)
        for cand in ("dp", "data", "x"):
            if cand in names:
                return cand
        return names[0]

    def _place(self):
        """Apply parameter placements: annotated specs (shard_tensor)
        sharded, everything else replicated — the reference partitioner."""
        mesh = self._mesh()
        if mesh is None or self._placed:
            return
        from ...parallel import _valid_spec
        for p in self.model.parameters():
            spec = p.sharding_spec
            sh = NamedSharding(mesh, P(*spec)) if (
                spec is not None and _valid_spec(p._data, spec, mesh)) \
                else NamedSharding(mesh, P())
            try:
                p._data = jax.device_put(p._data, sh)
            except Exception:
                pass
        self._placed = True

    def _log(self, entry):
        """Append to the per-engine reshard log under the shared
        1000-entry bound (one place owns the cap)."""
        self._reshard_log.append(entry)
        del self._reshard_log[:-1000]

    @staticmethod
    def _probe_pair_order(sub, lins):
        """Determine a Linear pair's DATAFLOW order by running the owning
        block's forward on a dummy batch with forward-pre hooks recording
        which Linear fires first. Returns (ordered_pair | None,
        'probed' | 'heuristic'). The dummy's feature dim is tried from
        both candidates' in_features (a wrong guess shape-errors and the
        other is tried)."""
        import numpy as _np
        order: list = []
        handles = [lin.register_forward_pre_hook(
            lambda layer, inp: order.append(layer)) for lin in lins]
        # probe in EVAL mode: no_grad() does not stop buffer updates — a
        # train-mode BatchNorm between the Linears would blend its
        # running stats toward the zero dummy, and dropout would consume
        # global RNG draws. Restore each layer's own flag afterwards
        # (states may be mixed).
        modes = [(lay, lay.training)
                 for lay in sub.sublayers(include_self=True)]
        sub.eval()
        try:
            for first in lins:
                order.clear()
                dummy = Tensor(_np.zeros(
                    (2, int(first.weight.shape[0])), _np.float32))
                try:
                    with no_grad():
                        sub(dummy)
                except Exception:
                    continue
                if len(order) >= 2 and order[0] is not order[1]:
                    return [order[0], order[1]], "probed"
        finally:
            for h in handles:
                h.remove()
            for lay, was in modes:
                lay.training = was
        return None, "heuristic"

    # ------------------------------------------------- placement search
    def search_mp_placements(self, sample_batch_shape, mp_axis="mp"):
        """Placement SEARCH over candidate model-parallel shardings (r5
        verdict #10; reference: auto_parallel/static/cost_model.py — the
        planner's op-level strategy search, realized here at BLOCK level:
        paired Linears, the unit Megatron's col-then-row rule applies to).

        For every sublayer owning exactly two chained Linears
        (W1: [K, F] feeding W2: [F, K] — an FFN block or an
        attention out-projection pair), score the candidate placements
        over the mesh's `mp_axis` by estimated PER-STEP collective bytes
        (B*S tokens from sample_batch_shape):

          col_row  — W1 P(None, mp), W2 P(mp, None): the partial-sum
                     output of the row-parallel W2 needs one psum of the
                     [B*S, K] activation fwd + one in bwd  -> 2*act_bytes
          row_col  — W1 P(mp, None), W2 P(None, mp): the input must be
                     gathered/summed around BOTH matmuls -> 4*act_bytes
          replicate — zero comm but no memory scaling (kept as the
                     fallback when a pair's weights don't divide).

        The cheaper sharded plan wins; the decision (with both scores,
        bytes-moved to get there, and the per-device memory win) is
        appended to the reshard log, and the placements are APPLIED.
        Returns the number of pair blocks sharded."""
        mesh = self._mesh()
        if mesh is None or mp_axis not in mesh.axis_names:
            return 0
        mp = dict(mesh.shape)[mp_axis]
        if mp < 2:
            return 0
        from ...nn.layer.common import Linear
        from ...parallel import _valid_spec
        tokens = int(np.prod(sample_batch_shape))
        n_sharded = 0
        for name, sub in self.model.named_sublayers(include_self=True):
            lins = [c for c in sub.children() if isinstance(c, Linear)]
            if len(lins) != 2:
                continue
            w1, w2 = lins[0].weight, lins[1].weight
            if w1.shape[1] != w2.shape[0]:
                continue        # not a chained pair
            # declaration order is not dataflow order, and shapes alone
            # cannot distinguish a reversed FFN from an in-order
            # bottleneck ([K,F],[F,K] chains either way). PROBE the real
            # order: forward-pre hooks on both Linears + a dummy forward
            # of the owning block record which fires first. Only when the
            # probe fails fall back to the expander-first heuristic —
            # and say so in the log instead of asserting the cheap name.
            ordered, orientation = self._probe_pair_order(sub, lins)
            if ordered is not None:
                w1, w2 = ordered[0].weight, ordered[1].weight
            elif int(w1.shape[1]) < int(w1.shape[0]) and \
                    int(w2.shape[1]) > int(w2.shape[0]):
                w1, w2 = w2, w1
            k = int(w1.shape[0])
            itemsize = w1._data.dtype.itemsize
            act_bytes = tokens * k * itemsize
            cand = {
                "col_row": {"w1": P(None, mp_axis), "w2": P(mp_axis, None),
                            "comm_bytes_per_step": 2 * act_bytes},
                "row_col": {"w1": P(mp_axis, None), "w2": P(None, mp_axis),
                            "comm_bytes_per_step": 4 * act_bytes},
            }
            valid = {nm: c for nm, c in cand.items()
                     if _valid_spec(w1._data, c["w1"], mesh)
                     and _valid_spec(w2._data, c["w2"], mesh)}
            if not valid:
                continue        # indivisible: stay replicated (0 comm)
            best = min(valid, key=lambda nm: valid[nm]
                       ["comm_bytes_per_step"])
            plan = valid[best]
            # snapshot for exact rollback: restoring the saved arrays
            # restores the PRE-ATTEMPT placement (which may itself have
            # been sharded by an earlier pass — forcing P() would
            # destroy it)
            snap = [(w, w._data, w.sharding_spec) for w in (w1, w2)]
            moved, done = 0, []
            for w, spec in ((w1, plan["w1"]), (w2, plan["w2"])):
                try:
                    w._data = jax.device_put(
                        w._data, NamedSharding(mesh, spec))
                except Exception:
                    break
                w.sharding_spec = spec
                moved += int(w._data.nbytes)
                done.append(w)
            from .api import bump_placement_generation
            if len(done) != 2:
                # half-applied placement is worse than none (the log
                # would claim a memory win reality doesn't have):
                # restore the pre-attempt state exactly, and bump the
                # generation anyway — a weight may have moved and moved
                # back, and plan caches must not assume nothing changed
                for w, data, spec in snap:
                    w._data = data
                    w.sharding_spec = spec
                bump_placement_generation()
                self._log({
                    "decision": "mp_placement:failed", "block": name,
                    "why": "device_put failed mid-pair; restored "
                           "pre-attempt placements"})
                continue
            bump_placement_generation()
            pair_bytes = int(w1._data.nbytes) + int(w2._data.nbytes)
            self._log({
                "decision": f"mp_placement:{best}", "block": name,
                "orientation": orientation,
                "candidates": {nm: c["comm_bytes_per_step"]
                               for nm, c in valid.items()},
                "comm_bytes_per_step": plan["comm_bytes_per_step"],
                "bytes_moved": moved,
                "mem_per_device_bytes": pair_bytes // mp,
                "why": (f"{best} minimizes per-step collective bytes "
                        f"({plan['comm_bytes_per_step']} vs "
                        + ", ".join(f"{nm}={c['comm_bytes_per_step']}"
                                    for nm, c in valid.items()
                                    if nm != best)
                        + ("; orientation probed from dataflow" if
                           orientation == "probed" else
                           "; orientation ASSUMED by shape heuristic")
                        + ")")})
            n_sharded += 1
        return n_sharded

    def _axis_conflict_plan(self, arr, mesh):
        """The planner decision the reference's cost model makes
        (auto_parallel/static/cost_model.py + Resharder): when the batch's
        data axis is ALSO claimed by parameter placements (one mesh axis
        cannot shard both the batch and the weights), choose the cheaper
        repair by bytes-moved and LOG the decision:

          reshard_input  — keep the annotated model-parallel placements,
                           replicate the batch (costs input bytes/step);
          reshard_params — strip the conflicting parameter shardings to
                           replicated, keep the batch data-parallel
                           (costs the conflicting params' bytes).

        The decision is made ONCE per input signature — from the MODEL
        INPUT only; labels follow the input's batch placement rather than
        voting with their own sizes (two arrays reaching contradictory
        plans in one step would undo each other). Returns the plan name:
        'data_parallel' (no conflict), 'reshard_input', or
        'reshard_params'.

        The cached plan short-circuits BEFORE the O(n_params) conflict
        scan (the scan would otherwise run in the hot input path every
        step). A reshard_params decision is only cached once every strip
        succeeded — a transient device_put failure leaves the plan
        uncached so the next batch retries the remaining strips instead
        of silently training with the conflict unrepaired."""
        input_bytes = int(getattr(arr, "nbytes", np.asarray(arr).nbytes))
        ax = self._data_axis(mesh)
        # the placement generation invalidates cached plans whenever any
        # annotation API re-shards a tensor: a plan computed against old
        # placements must not suppress repair of a NEW conflict. The
        # generation is compared (and the cache cleared on mismatch)
        # rather than folded into the key — per-step annotation traffic
        # (e.g. stage-2 reshard_grads calling with_spec per gradient)
        # would otherwise make every lookup miss AND grow the dict one
        # stale entry per batch.
        from .api import placement_generation
        gen = placement_generation()
        if getattr(self, "_plan_gen", None) != gen:
            self._conflict_plan.clear()
            self._plan_gen = gen
        key = (ax, input_bytes)
        plan = self._conflict_plan.get(key)
        if plan is not None:
            return plan
        from ...parallel import _valid_spec
        # only REAL on-device conflicts count: a spec _place rejected as
        # indivisible left the param replicated — no repair needed
        conflicts = [p for p in self.model.parameters()
                     if p.sharding_spec is not None
                     and ax in tuple(p.sharding_spec)
                     and _valid_spec(p._data, p.sharding_spec, mesh)]
        if not conflicts:
            self._conflict_plan[key] = "data_parallel"
            return "data_parallel"
        param_bytes = sum(int(p._data.nbytes) for p in conflicts)
        plan = ("reshard_input" if input_bytes <= param_bytes
                else "reshard_params")
        self._log({
            "decision": plan, "axis": ax,
            "input_bytes": input_bytes, "param_bytes": param_bytes,
            "conflicting_params": len(conflicts)})
        failed = 0
        if plan == "reshard_params":
            for p in conflicts:
                try:
                    p._data = jax.device_put(
                        p._data, NamedSharding(mesh, P()))
                except Exception:
                    failed += 1
                    continue   # still sharded: keep spec + no log
                p.sharding_spec = None
                self._log({
                    "shape": tuple(p.shape), "from": "annotated",
                    "to": "P()", "bytes_moved": int(p._data.nbytes)})
            if failed:
                attempts = self._strip_attempts = getattr(
                    self, "_strip_attempts", 0) + 1
                self._log({
                    "decision": plan, "strip_failed": failed,
                    "attempt": attempts,
                    "note": "plan not cached; retried next batch"
                    if attempts < 3 else
                    "giving up after 3 attempts; conflict unrepaired"})
                if attempts >= 3:   # bound the per-step rescan + log
                    self._conflict_plan[key] = plan
        if not failed:
            self._conflict_plan[key] = plan
        return plan

    def _shard_batch(self, arr, mesh, replicate=False):
        """Batch placement WITH the reshard pass: an input that arrives
        mis-sharded (wrong spec, or a different mesh entirely) is moved to
        the data-parallel layout rather than erroring; the move is costed
        in the reshard log (reference: Resharder + cost model).
        replicate=True (the planner chose reshard_input) places the array
        replicated instead of data-sharded."""
        from .api import _reshard_array
        ax = self._data_axis(mesh)
        if arr.shape[0] % mesh.shape[ax] == 0:
            spec = P(*([None] * arr.ndim)) if replicate else \
                P(ax, *([None] * (arr.ndim - 1)))
            cur = getattr(arr, "sharding", None)
            out, moved = _reshard_array(arr, mesh, spec)
            # cost-log only true reshards — a mesh-committed input whose
            # placement disagreed — not routine host→device feeding
            if moved and isinstance(cur, NamedSharding):
                self._log({
                    "shape": tuple(np.shape(arr)), "from": str(cur.spec),
                    "to": str(spec), "bytes_moved": moved})
            return out
        return arr

    def _build_step(self):
        from ... import jit as pjit

        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer

        @pjit.to_static
        def step(x, y):
            out = model(x)
            loss = loss_fn(out, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        @pjit.to_static
        def eval_step(x, y):
            with no_grad():
                out = model(x)
                return loss_fn(out, y), out

        return step, eval_step

    def _loader(self, data, batch_size, shuffle):
        from ...io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        raise TypeError(f"expected Dataset/DataLoader, got {type(data)}")

    def _prep_batch(self, batch, mesh):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            x, y = batch[0], batch[1]
        elif isinstance(batch, (list, tuple)) and len(batch) == 1:
            x, y = batch[0], None
        else:
            x, y = batch, None
        if mesh is not None:
            x_arr = x._data if isinstance(x, Tensor) else np.asarray(x)
            # ONE planner decision per step, made from the model input;
            # labels follow the input's batch placement (their own size
            # must not cast a contradictory vote)
            replicate = self._axis_conflict_plan(
                x_arr, mesh) == "reshard_input"
            x = Tensor(self._shard_batch(x_arr, mesh, replicate))
            if y is not None:
                y = Tensor(self._shard_batch(
                    y._data if isinstance(y, Tensor) else np.asarray(y),
                    mesh, replicate))
        return x, y

    # ------------------------------------------------------------ public
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        self._place()
        if self._step_fn is None:
            self._step_fn, self._eval_fn = self._build_step()

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            valid_data=None, valid_freq=1, log_freq=10, verbose=0,
            callbacks=None, collate_fn=None):
        assert self.model is not None and self.optimizer is not None and \
            self.loss_fn is not None, "Engine needs model, loss, optimizer"
        self.model.train()
        self.prepare()
        mesh = self._mesh()
        loader = self._loader(train_data, batch_size, shuffle=True)
        hist = _History()
        for epoch in range(epochs):
            for step_idx, batch in enumerate(loader):
                if steps_per_epoch and step_idx >= steps_per_epoch:
                    break
                x, y = self._prep_batch(batch, mesh)
                loss = self._step_fn(x, y)
                lv = float(np.asarray(loss._data).mean())
                hist.history["loss"].append(lv)
                if verbose and step_idx % log_freq == 0:
                    print(f"epoch {epoch} step {step_idx}: loss {lv:.4f}")
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                self.evaluate(valid_data, batch_size=batch_size,
                              verbose=verbose)
                self.model.train()
        return hist

    def evaluate(self, valid_data, batch_size=1, steps=None, verbose=0,
                 collate_fn=None):
        self.model.eval()
        self.prepare()
        mesh = self._mesh()
        loader = self._loader(valid_data, batch_size, shuffle=False)
        losses = []
        for m in self.metrics:
            m.reset()
        for step_idx, batch in enumerate(loader):
            if steps and step_idx >= steps:
                break
            x, y = self._prep_batch(batch, mesh)
            loss, out = self._eval_fn(x, y)
            losses.append(float(np.asarray(loss._data).mean()))
            for m in self.metrics:
                m.update(m.compute(out, y))
        result = {"loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            result[m.name() if callable(getattr(m, "name", None)) else
                   type(m).__name__] = m.accumulate()
        if verbose:
            print(f"eval: {result}")
        return result

    @no_grad()
    def predict(self, test_data, batch_size=1, steps=None, collate_fn=None):
        self.model.eval()
        mesh = self._mesh()
        self._place()
        outs = []
        loader = self._loader(test_data, batch_size, shuffle=False)
        for step_idx, batch in enumerate(loader):
            if steps and step_idx >= steps:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            x, _ = self._prep_batch([x, None], mesh)
            outs.append(self.model(x))
        return outs

    def save(self, path, training=True):
        from ...framework.io import save
        state = {"model": self.model.state_dict()}
        if training and self.optimizer is not None:
            state["opt"] = self.optimizer.state_dict()
        save(state, path)

    def load(self, path):
        from ...framework.io import load
        state = load(path)
        self.model.set_state_dict(state["model"])
        if "opt" in state and self.optimizer is not None:
            self.optimizer.set_state_dict(state["opt"])
