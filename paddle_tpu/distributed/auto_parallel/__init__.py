from .api import (ProcessMesh, shard_tensor, shard_op, get_mesh, set_mesh,
                  dtensor_from_fn, reshard, reshard_cost_log,
                  clear_reshard_cost_log, Shard, Replicate, Partial)
from .engine import Engine
