"""Structured JSON-lines event logging for the distributed runtime.

``PADDLE_LOG_JSON=1`` switches the gang supervisor's and the
watchdog's human-oriented prints into ONE JSON object per line —
machine-ingestible worker logs for a cluster front-end (restart /
failure / heartbeat events with rank, supervisor generation, and both
monotonic and wall-clock timestamps). With the flag off, ``log_event``
prints the caller's plain ``message`` unchanged (or stays silent when
there is none), so the default log format is exactly what it always
was.

Import-light by design (stdlib only): the launcher and the watchdog's
failure path must never grow a heavy dependency.
"""
from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["json_logging_enabled", "log_event"]


def json_logging_enabled() -> bool:
    return os.environ.get("PADDLE_LOG_JSON") == "1"


def log_event(component: str, event: str, message: str | None = None,
              stream=None, **fields):
    """Emit one runtime event.

    JSON mode: one object per line —
    ``{"component", "event", "rank", "generation", "pid", "t_wall",
    "t_mono", **fields}`` (rank from PADDLE_TRAINER_ID, None for the
    supervisor itself; generation from PADDLE_RESTART_COUNT; pid so
    events correlate with the flight recorder's pid-per-rank traces and
    dump headers). Plain mode: prints ``message`` verbatim when given,
    else silent (events that never had a print — e.g. clean exits —
    only surface in JSON mode). The supervisor's ``gang_diagnosis``
    event carries the cross-rank flight diagnosis this way: plain mode
    prints the human text, JSON mode the structured verdict.
    """
    out = stream if stream is not None else sys.stdout
    if not json_logging_enabled():
        if message is not None:
            print(message, file=out, flush=True)
        return
    rank_env = os.environ.get("PADDLE_TRAINER_ID")
    rec = {
        "component": component,
        "event": event,
        "rank": int(rank_env) if rank_env not in (None, "") else None,
        "generation": int(os.environ.get("PADDLE_RESTART_COUNT", "0")
                          or 0),
        "pid": os.getpid(),
        "t_wall": round(time.time(), 6),
        "t_mono": round(time.monotonic(), 6),
    }
    if message is not None:
        rec["message"] = message
    rec.update(fields)
    try:
        line = json.dumps(rec, default=str)
    except (TypeError, ValueError):
        line = json.dumps({k: str(v) for k, v in rec.items()})
    print(line, file=out, flush=True)
