"""paddle.distributed.all_reduce. Parity: communication/all_reduce.py."""
from __future__ import annotations

from ...tensor.tensor import Tensor
from ..resilience.flight_recorder import instrumented as _instrumented
from .group import ReduceOp, _default_group

__all__ = ["all_reduce"]


@_instrumented("all_reduce")
def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    from .group import Task
    g = group or _default_group()
    # static capture: record the collective into the Program (the
    # reference's c_allreduce_sum op in ProgramDesc)
    from .ops import _capture_collective
    t = _capture_collective(tensor, lambda a: g.pg.allreduce(a, op))
    if t is not None:
        return t
    out = g.pg.allreduce(tensor._data, op)
    tensor._data = out
    return Task(out)
