"""paddle.distributed.all_reduce. Parity: communication/all_reduce.py."""
from __future__ import annotations

from ...tensor.tensor import Tensor
from .group import ReduceOp, _default_group

__all__ = ["all_reduce"]


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    g = group or _default_group()
    out = g.pg.allreduce(tensor._data, op)
    tensor._data = out
    from .group import Task
    return Task(out)
