"""Process groups over XLA collectives.

Parity: paddle/fluid/distributed/collective/process_group.h :: ProcessGroup +
process_group_nccl.cc :: ProcessGroupNCCL. The TPU-native ProcessGroupXLA
realizes the same interface as compiled XLA collectives over a device mesh
(ICI within a slice, DCN across slices); there are no comm streams or events
to manage — XLA's async dispatch and latency-hiding scheduler replace them.

Execution contexts served:
  * traced (inside shard_map/pjit): collectives lower to lax.psum/all_gather/
    ppermute/all_to_all over the group's mesh axis name;
  * eager multi-process: the local array is treated as this process's shard of
    a global array; a cached one-op jitted shard_map program runs the
    collective (SURVEY §7 hard part 2 — cache key = op/shape/dtype/group);
  * eager single-process: world of 1 → identity (matches reference semantics
    of a 1-rank group).
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..resilience.flight_recorder import instrumented as _instrumented

__all__ = ["ReduceOp", "Group", "ProcessGroupXLA", "new_group", "get_group",
           "destroy_process_group", "is_initialized", "_ensure_default_group",
           "_default_group", "wait"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Task:
    """Parity: ProcessGroup::Task — XLA dispatch is already async; wait()
    blocks on the result buffer."""

    def __init__(self, result=None):
        self._result = result

    def wait(self, timeout=None):
        if self._result is not None and hasattr(self._result, "block_until_ready"):
            self._result.block_until_ready()
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        self.wait()


class ProcessGroupXLA:
    """The ProcessGroupNCCL replacement: collectives as compiled XLA programs."""

    def __init__(self, ranks: Sequence[int], group_id: int = 0,
                 axis_name: Optional[str] = None, mesh: Optional[Mesh] = None):
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.group_id = group_id
        # axis_name set when this group corresponds to a mesh axis (fleet
        # topology); used to lower collectives inside traced code.
        self.axis_name = axis_name
        self.mesh = mesh
        self._jit_cache: dict = {}

    # -------------------------------------------------------------- helpers
    def _in_trace(self, arr) -> bool:
        return isinstance(arr, jax.core.Tracer)

    def _axis(self) -> str:
        return self.axis_name or "ranks"

    def _spmd(self, arr, lax_fn):
        """Inside shard_map/pjit: apply the lax collective on the axis."""
        return lax_fn(arr, self._axis())

    def _eager_mesh(self) -> Optional[Mesh]:
        if self.mesh is not None:
            return self.mesh
        if jax.process_count() == 1:
            return None
        # one device PER PROCESS: each rank must address exactly its own
        # shard (hosts may expose several local devices, e.g. a virtual
        # CPU mesh — taking jax.devices()[:n] could land two mesh slots in
        # one process and break make_array_from_process_local_data)
        by_proc: dict[int, object] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        members = self.ranks if self.ranks else sorted(by_proc)[: self.nranks]
        devs = np.array([by_proc[r] for r in members])
        return Mesh(devs, ("ranks",))

    def _run_sharded(self, key, arr, fn, out_spec=None):
        """Cached shard_map program over the group mesh (multi-process path)."""
        from jax import shard_map
        mesh = self._eager_mesh()
        axis = self._axis()
        ck = (key, tuple(arr.shape), str(arr.dtype))
        if ck not in self._jit_cache:
            in_spec = P(axis)
            sm = shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                           out_specs=out_spec if out_spec is not None
                           else in_spec,
                           check_vma=False)
            self._jit_cache[ck] = jax.jit(sm)
        global_arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(axis)),
            np.asarray(arr)[None], (self.nranks, *arr.shape))
        out = self._jit_cache[ck](global_arr)
        local = [s.data for s in out.addressable_shards]
        return np.asarray(local[0])

    # ----------------------------------------------------------- collectives
    @_instrumented("pg_allreduce")
    def allreduce(self, arr, op=ReduceOp.SUM):
        import jax.lax as lax
        red = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
               ReduceOp.MIN: lax.pmin,
               ReduceOp.AVG: lambda x, a: lax.pmean(x, a)}.get(op, lax.psum)
        if self._in_trace(arr):
            return red(arr, self._axis())
        if self.nranks <= 1 or jax.process_count() == 1:
            return arr
        return jnp.asarray(self._run_sharded(
            ("allreduce", op), arr,
            lambda x: red(x, self._axis()))[0])

    @_instrumented("pg_allgather")
    def allgather(self, arr):
        import jax.lax as lax
        if self._in_trace(arr):
            return lax.all_gather(arr, self._axis())
        if self.nranks <= 1 or jax.process_count() == 1:
            return jnp.asarray(arr)[None]
        # replicated out_spec: every rank materializes the full [n, ...]
        return jnp.asarray(self._run_sharded(
            ("allgather",), arr,
            lambda x: lax.all_gather(x[0], self._axis()), out_spec=P()))

    @_instrumented("pg_reducescatter")
    def reducescatter(self, arr, op=ReduceOp.SUM):
        import jax.lax as lax
        if self._in_trace(arr):
            return lax.psum_scatter(arr, self._axis(), tiled=True)
        if self.nranks <= 1 or jax.process_count() == 1:
            return arr
        # rank-varying chunks: out_spec over the axis, my addressable
        # shard IS my chunk
        return jnp.asarray(self._run_sharded(
            ("reducescatter", op), arr,
            lambda x: lax.psum_scatter(x[0], self._axis(), tiled=True)))

    @_instrumented("pg_broadcast")
    def broadcast(self, arr, src_group_rank=0):
        import jax.lax as lax
        if self._in_trace(arr):
            full = lax.all_gather(arr, self._axis())
            return full[src_group_rank]
        if self.nranks <= 1 or jax.process_count() == 1:
            return arr
        return jnp.asarray(self._run_sharded(
            ("broadcast", src_group_rank), arr,
            lambda x: lax.all_gather(x[0], self._axis())[src_group_rank],
            out_spec=P()))

    @_instrumented("pg_alltoall")
    def alltoall(self, arr):
        import jax.lax as lax
        if self._in_trace(arr):
            return lax.all_to_all(arr, self._axis(), split_axis=0,
                                  concat_axis=0, tiled=True)
        if self.nranks <= 1 or jax.process_count() == 1:
            return arr
        return jnp.asarray(self._run_sharded(
            ("alltoall",), arr,
            lambda x: lax.all_to_all(x[0], self._axis(), 0, 0, tiled=True)))

    @_instrumented("pg_permute")
    def permute(self, arr, perm):
        """ppermute: perm is a list of (src, dst) group-rank pairs."""
        import jax.lax as lax
        if self._in_trace(arr):
            return lax.ppermute(arr, self._axis(), perm)
        if self.nranks <= 1 or jax.process_count() == 1:
            return arr
        return jnp.asarray(self._run_sharded(
            ("ppermute", tuple(map(tuple, perm))), arr,
            lambda x: lax.ppermute(x, self._axis(), perm))[0])

    @_instrumented("pg_barrier")
    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"pg_{self.group_id}_barrier")
        return Task()


class Group:
    """Parity: python/paddle/distributed/communication/group.py :: Group."""

    def __init__(self, rank_in_group, group_id, ranks, pg=None, name=None):
        self.rank = rank_in_group
        self.id = group_id
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.pg = pg or ProcessGroupXLA(self.ranks, group_id)
        self.name = name or f"group_{group_id}"

    @property
    def process_group(self):
        return self.pg

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def is_member(self):
        return self.rank >= 0

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_groups: dict[int, Group] = {}
_next_id = [0]


def _ensure_default_group() -> Group:
    if 0 not in _groups:
        from ..parallel import get_world_size, get_rank
        ws = max(get_world_size(), 1)
        ranks = list(range(ws))
        _groups[0] = Group(get_rank(), 0, ranks,
                           ProcessGroupXLA(ranks, 0))
    return _groups[0]


def _default_group() -> Group:
    return _ensure_default_group()


def new_group(ranks=None, backend=None, timeout=None, axis_name=None,
              mesh=None) -> Group:
    from ..parallel import get_rank, get_world_size
    if ranks is None:
        ranks = list(range(max(get_world_size(), 1)))
    _next_id[0] += 1
    gid = _next_id[0]
    me = get_rank()
    rank_in_group = ranks.index(me) if me in ranks else -1
    pg = ProcessGroupXLA(ranks, gid, axis_name=axis_name, mesh=mesh)
    g = Group(rank_in_group, gid, ranks, pg)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Optional[Group]:
    return _groups.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


def is_initialized() -> bool:
    from ..parallel import is_initialized_env
    return is_initialized_env()


def wait(tensor, group=None, use_calc_stream=True):
    if hasattr(tensor, "_data") and hasattr(tensor._data, "block_until_ready"):
        tensor._data.block_until_ready()
