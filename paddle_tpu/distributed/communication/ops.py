"""Collective functional API.

Parity: python/paddle/distributed/communication/{all_gather,broadcast,reduce,
scatter,all_to_all,send/recv,batch_isend_irecv}.py + stream/* async variants.
In-place semantics match the reference (result written back into the given
tensor / tensor_list).
"""
from __future__ import annotations

import pickle
from typing import Optional

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor
from ..resilience.flight_recorder import instrumented as _instrumented
from .group import ReduceOp, Task, _default_group

__all__ = ["all_gather", "all_gather_object", "broadcast",
           "broadcast_object_list", "reduce", "scatter",
           "scatter_object_list", "gather", "alltoall", "alltoall_single",
           "send", "recv", "isend", "irecv", "P2POp", "batch_isend_irecv",
           "barrier", "reduce_scatter", "get_backend", "stream"]


@_instrumented("all_gather")
def all_gather(tensor_list, tensor, group=None, sync_op=True):
    g = group or _default_group()
    gathered = g.pg.allgather(tensor._data)  # [nranks, ...]
    n = g.nranks
    tensor_list.clear()
    for i in range(max(n, 1)):
        tensor_list.append(Tensor(gathered[i] if gathered.ndim > tensor._data.ndim
                                  else gathered))
    return Task(gathered)


@_instrumented("all_gather_object")
def all_gather_object(object_list, obj, group=None):
    g = group or _default_group()
    if g.nranks <= 1:
        object_list.clear()
        object_list.append(obj)
        return
    import numpy as np
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # pad to the max length across ranks
    ln = Tensor(jnp.asarray([payload.size], jnp.int32))
    lens = []
    all_gather(lens, ln, group=g)
    maxlen = int(max(int(l._data[0]) for l in lens))
    buf = np.zeros(maxlen, np.uint8)
    buf[: payload.size] = payload
    outs = []
    all_gather(outs, Tensor(jnp.asarray(buf)), group=g)
    object_list.clear()
    for t, l in zip(outs, lens):
        raw = bytes(np.asarray(t._data)[: int(l._data[0])])
        object_list.append(pickle.loads(raw))


def _capture_collective(tensor, fn):
    """Static capture: record an in-place collective into the active
    Program (the reference's c_* collective ops in ProgramDesc); returns a
    Task when recorded, None when no capture is active."""
    from ...tensor.tensor import apply_op, _capture_hook
    if _capture_hook[0] is None:
        return None
    from ...static import _alias_capture_output
    out = apply_op(fn, tensor)
    tensor._data = out._data
    _alias_capture_output(out, tensor)
    return Task(out._data)


@_instrumented("broadcast")
def broadcast(tensor, src=0, group=None, sync_op=True):
    g = group or _default_group()
    src_in_group = g.get_group_rank(src) if g.ranks else src
    t = _capture_collective(
        tensor, lambda a: g.pg.broadcast(a, max(src_in_group, 0)))
    if t is not None:
        return t
    out = g.pg.broadcast(tensor._data, max(src_in_group, 0))
    tensor._data = out
    return Task(out)


@_instrumented("reduce")
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference semantics: only dst receives the reduction; other ranks'
    buffers are left as-is (XLA computes the allreduce — the cheapest ICI
    realization — but non-dst ranks discard it). Non-members no-op;
    dst must be in the group."""
    g = group or _default_group()
    if g.ranks and g.rank < 0:
        return Task()                       # this process isn't a member
    dst_in_group = g.get_group_rank(dst) if g.ranks else dst
    if dst_in_group < 0:
        raise ValueError(f"reduce: dst rank {dst} is not in the group")
    def _dst_gated(a):
        out_ = g.pg.allreduce(a, op)
        if isinstance(a, jax.core.Tracer) and g.pg.axis_name:
            me = jax.lax.axis_index(g.pg.axis_name)
            return jnp.where(me == dst_in_group, out_, a)
        if g.nranks <= 1 or max(g.rank, 0) == dst_in_group:
            return out_
        return a

    t = _capture_collective(tensor, _dst_gated)
    if t is not None:
        return t
    out = _dst_gated(tensor._data)
    tensor._data = out
    return Task(out)


@_instrumented("scatter")
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = group or _default_group()
    if g.nranks <= 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return Task()
    from ...tensor.tensor import _capture_hook
    if _capture_hook[0] is not None and tensor_list:
        from ...tensor.tensor import apply_op
        from ...static import _alias_capture_output
        me = max(g.rank, 0)
        src_gr = max(g.get_group_rank(src), 0)

        def f(*arrs):
            full = g.pg.broadcast(jnp.stack(arrs), src_gr)
            return full[me]
        out = apply_op(f, *tensor_list)
        tensor._data = out._data
        _alias_capture_output(out, tensor)
        return Task(out._data)
    # src rank provides tensor_list; realized as broadcast-of-stack + index.
    # XLA has no single-source variadic scatter primitive; on the ICI torus
    # a broadcast is a pipelined ring and non-dst chunks are dead-code at
    # the slice, so the practical cost matches a hand-rolled scatter for
    # the small control tensors this API is used for (EP dispatch uses
    # alltoall, not this).
    stacked = (jnp.stack([t._data for t in tensor_list])
               if tensor_list else jnp.zeros((g.nranks, *tensor.shape),
                                             tensor.dtype))
    full = g.pg.broadcast(stacked, max(g.get_group_rank(src), 0))
    me = max(g.rank, 0)
    tensor._data = full[me]
    return Task()


@_instrumented("alltoall")
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    g = group or _default_group()
    if isinstance(in_tensor_list, Tensor):
        # tensor-form alltoall
        out = g.pg.alltoall(in_tensor_list._data)
        return Tensor(out)
    stacked = jnp.concatenate([t._data[None] if t.ndim == len(in_tensor_list[0].shape)
                               else t._data for t in in_tensor_list], axis=0)
    out = g.pg.alltoall(stacked)
    n = max(g.nranks, 1)
    if out_tensor_list is None:
        out_tensor_list = []
    out_tensor_list.clear()
    chunk = out.shape[0] // n
    for i in range(n):
        out_tensor_list.append(Tensor(out[i * chunk:(i + 1) * chunk].squeeze(0)
                                      if chunk == 1 else
                                      out[i * chunk:(i + 1) * chunk]))
    return Task(out)


@_instrumented("alltoall_single")
def alltoall_single(in_tensor, out_tensor=None,
                    in_split_sizes=None, out_split_sizes=None, group=None,
                    sync_op=True):
    g = group or _default_group()
    out = g.pg.alltoall(in_tensor._data)
    if out_tensor is not None:
        out_tensor._data = out
        return Task(out)
    return Tensor(out)


# Point-to-point: realized as ppermute pairs (ICI neighbor exchange).
@_instrumented("send")
def send(tensor, dst=0, group=None, sync_op=True):
    g = group or _default_group()
    me = max(g.rank, 0)
    g.pg.permute(tensor._data, [(me, g.get_group_rank(dst) if g.ranks else dst)])
    return Task()


@_instrumented("recv")
def recv(tensor, src=0, group=None, sync_op=True):
    g = group or _default_group()
    me = max(g.rank, 0)
    out = g.pg.permute(tensor._data,
                       [(g.get_group_rank(src) if g.ranks else src, me)])
    tensor._data = out
    return Task(out)


@_instrumented("isend")
def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


@_instrumented("irecv")
def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


@_instrumented("broadcast_object_list")
def broadcast_object_list(object_list, src=0, group=None):
    """Broadcast a list of picklable objects from src (reference:
    communication/broadcast.py :: broadcast_object_list). Realized over
    all_gather_object — on the ICI torus a gather-and-pick costs the same
    ring traversal as a broadcast for the small control payloads this
    API carries."""
    g = group or _default_group()
    if g.nranks <= 1:
        return
    if g.ranks and g.rank < 0:
        return                      # not a member of this group: no-op
    src_gr = g.get_group_rank(src) if g.ranks else src
    if src_gr < 0 or src_gr >= g.nranks:
        raise ValueError(f"src {src} is not in the group")
    # only src's payload is serialized — non-src ranks contribute None so
    # their placeholder contents need not be picklable (reference
    # semantics); the gather costs one payload + (n-1) None pickles
    mine = list(object_list) if max(g.rank, 0) == src_gr else None
    gathered = []
    all_gather_object(gathered, mine, group=g)
    object_list[:] = gathered[src_gr]


@_instrumented("scatter_object_list")
def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter one picklable object per rank from src (reference:
    communication/scatter.py :: scatter_object_list)."""
    g = group or _default_group()
    if g.nranks <= 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return
    if g.ranks and g.rank < 0:
        return                      # not a member of this group: no-op
    src_gr = g.get_group_rank(src) if g.ranks else src
    if max(g.rank, 0) == src_gr and len(in_object_list or []) != g.nranks:
        # loud at the call site (reference errors here too) — a short
        # list would broadcast fine and only fail ranks >= len(payload)
        # later with an opaque IndexError
        raise ValueError(
            f"scatter_object_list: src needs one object per rank "
            f"(got {len(in_object_list or [])}, nranks {g.nranks})")
    payload = list(in_object_list or [None] * g.nranks)
    broadcast_object_list(payload, src=src, group=g)
    me = max(g.rank, 0)
    out_object_list[:] = [payload[me]]


@_instrumented("gather")
def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather tensors onto dst (reference: communication/gather.py).
    All-ranks allgather + keep-on-dst: XLA collectives are SPMD — every
    rank executes the same program, and non-dst ranks simply drop the
    result (dead code at their slice)."""
    g = group or _default_group()
    if g.nranks <= 1:
        if gather_list is not None:
            gather_list.clear()
            gather_list.append(Tensor(tensor._data))
        return Task()
    if g.ranks and g.rank < 0:
        return Task()               # not a member of this group: no-op
    dst_gr = g.get_group_rank(dst) if g.ranks else dst
    if dst_gr < 0 or dst_gr >= g.nranks:
        raise ValueError(f"dst {dst} is not in the group")
    outs = []
    t = all_gather(outs, tensor, group=g)
    if gather_list is not None and max(g.rank, 0) == dst_gr:
        gather_list.clear()
        gather_list.extend(outs)
    return t


class P2POp:
    """One batched point-to-point descriptor (reference:
    communication/batch_isend_irecv.py :: P2POp): op is the module-level
    isend/irecv function; executed by batch_isend_irecv."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise ValueError("P2POp op must be paddle.distributed.isend "
                             "or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


@_instrumented("batch_isend_irecv")
def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2POps; returns their Tasks. On TPU each pair
    lowers to a ppermute — XLA fuses/pipelines the batch over ICI, so
    batching here is API parity (the reference batches to share one NCCL
    group call)."""
    if not p2p_op_list:
        return []
    return [op.op(op.tensor, op.peer, op.group) for op in p2p_op_list]


def get_backend(group=None):
    """Backend name of the group (reference returns 'NCCL'/'GLOO'): the
    TPU realization is XLA collectives over ICI/DCN."""
    return "XLA"


@_instrumented("barrier")
def barrier(group=None):
    g = group or _default_group()
    return g.pg.barrier()


@_instrumented("reduce_scatter")
def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    g = group or _default_group()
    if tensor_list is not None:
        stacked = jnp.concatenate([t._data for t in tensor_list], axis=0)
    else:
        stacked = tensor._data
    out = g.pg.reducescatter(stacked, op)
    tensor._data = out
    return Task(out)


class _StreamNS:
    """paddle.distributed.stream.* async variants (sync_op=False parity)."""

    @staticmethod
    def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
                   use_calc_stream=False):
        from .all_reduce import all_reduce as _ar
        return _ar(tensor, op, group, sync_op)

    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    alltoall = staticmethod(alltoall)
    alltoall_single = staticmethod(alltoall_single)
    send = staticmethod(send)
    recv = staticmethod(recv)
    reduce_scatter = staticmethod(reduce_scatter)


stream = _StreamNS()
