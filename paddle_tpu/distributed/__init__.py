"""paddle.distributed — collective API + fleet (full build in parallel/ and
fleet/; this module re-exports the user surface).

Parity: python/paddle/distributed/__init__.py.
"""
from __future__ import annotations

from .parallel import (init_parallel_env, get_rank, get_world_size,
                       ParallelEnv, all_reduce_gradients)
from .communication.all_reduce import all_reduce
from .communication.group import (new_group, get_group, destroy_process_group,
                                  is_initialized, ReduceOp, Group)
from .communication.ops import (all_gather, all_gather_object, broadcast,
                                broadcast_object_list, reduce, scatter,
                                scatter_object_list, gather, alltoall,
                                alltoall_single, send, recv, isend, irecv,
                                P2POp, batch_isend_irecv, barrier,
                                reduce_scatter, get_backend, stream)
from . import fleet
from . import sharding
from .auto_parallel.api import shard_tensor, ProcessMesh, shard_op
from .spawn_mod import spawn
from .checkpoint import (save_state_dict, load_state_dict,
                         wait_all_async_saves, save_checkpoint,
                         load_latest, latest_step)
from .resilience import (PeerFailureError, monitored_barrier,
                         check_peer_failure)

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "all_reduce", "all_gather", "broadcast", "reduce", "scatter", "alltoall",
    "alltoall_single", "send", "recv", "isend", "irecv", "barrier",
    "reduce_scatter", "new_group", "get_group", "ReduceOp", "fleet",
    "sharding", "shard_tensor", "ProcessMesh", "spawn", "is_initialized",
    "save_checkpoint", "load_latest", "latest_step", "PeerFailureError",
    "monitored_barrier", "check_peer_failure",
]
from . import rpc  # noqa: E402  (reference: paddle.distributed.rpc)
__all__.append("rpc")
