"""paddle.audio.backends — WAV load/save over the stdlib `wave` module.
Parity: python/paddle/audio/backends/ (wave_backend.py :: load, save, info).
PCM 16/32-bit and 8-bit unsigned supported; float tensors in [-1, 1]."""
from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["load", "save", "info", "AudioInfo"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


_WIDTH2DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as w:
        return AudioInfo(sample_rate=w.getframerate(),
                         num_samples=w.getnframes(),
                         num_channels=w.getnchannels(),
                         bits_per_sample=8 * w.getsampwidth())


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """→ (Tensor [channels, time] (or [time, channels]), sample_rate)."""
    with wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        nch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
    dtype = _WIDTH2DTYPE[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16"):
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        arr = arr[:, None]       # mono → [time, 1] regardless of layout
    elif channels_first:
        arr = arr.T              # → [time, channels]
    width = {"PCM_16": 2, "PCM_32": 4, "PCM_U8": 1}[encoding]
    if np.issubdtype(arr.dtype, np.floating):
        if width == 1:
            pcm = np.clip(arr * 128.0 + 128.0, 0, 255).astype(np.uint8)
        else:
            scale = float(2 ** (8 * width - 1) - 1)
            pcm = np.clip(arr * scale, -scale - 1, scale).astype(
                _WIDTH2DTYPE[width])
    else:
        pcm = arr.astype(_WIDTH2DTYPE[width])
    with wave.open(filepath, "wb") as w:
        w.setnchannels(pcm.shape[1])
        w.setsampwidth(width)
        w.setframerate(int(sample_rate))
        w.writeframes(np.ascontiguousarray(pcm).tobytes())
