"""paddle.audio.features. Parity: python/paddle/audio/features/layers.py ::
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC.

TPU shape: framing is a gather into [frames, n_fft], the STFT is one batched
rFFT HLO, and mel/DCT projections are MXU matmuls — no per-frame loops."""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor, apply_op
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _stft_power(x, n_fft, hop_length, win, center, power,
                pad_mode="reflect"):
    """x: [..., T] → power spectrogram [..., freq, frames]."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    t = x.shape[-1]
    n_frames = 1 + (t - n_fft) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]
    frames = jnp.take(x, idx, axis=-1)          # [..., frames, n_fft]
    frames = frames * win
    spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
    mag = jnp.abs(spec)
    if power != 1.0:
        mag = mag ** power
    return jnp.swapaxes(mag, -1, -2)            # [..., freq, frames]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: int | None = None,
                 win_length: int | None = None, window: str = "hann",
                 power: float = 2.0, center: bool = True, pad_mode:
                 str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length, fftbins=True)._data
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.window = w

    def forward(self, x: Tensor) -> Tensor:
        n_fft, hop, win = self.n_fft, self.hop_length, self.window
        center, power, pad_mode = self.center, self.power, self.pad_mode
        return apply_op(
            lambda a: _stft_power(a, n_fft, hop, win, center, power,
                                  pad_mode), x)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, n_mels: int = 64, f_min: float = 50.0,
                 f_max: float | None = None, htk: bool = False,
                 norm: str = "slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center)
        self.fbank = compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm)._data

    def forward(self, x: Tensor) -> Tensor:
        spec = self.spectrogram(x)
        fb = self.fbank
        return apply_op(lambda s: jnp.einsum("mf,...ft->...mt", fb, s), spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int | None = None, win_length: int | None = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, n_mels: int = 64, f_min: float = 50.0,
                 f_max: float | None = None, htk: bool = False,
                 norm: str = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: float | None = None,
                 dtype: str = "float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, n_mels, f_min, f_max, htk,
                                  norm)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x: Tensor) -> Tensor:
        return power_to_db(self.mel(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: int | None = None, n_mels: int = 64,
                 f_min: float = 50.0, f_max: float | None = None,
                 top_db: float | None = None, dtype: str = "float32",
                 **mel_kwargs):
        super().__init__()
        self.logmel = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, n_mels=n_mels,
            f_min=f_min, f_max=f_max, top_db=top_db, **mel_kwargs)
        self.dct = create_dct(n_mfcc, n_mels)._data

    def forward(self, x: Tensor) -> Tensor:
        lm = self.logmel(x)
        dct = self.dct
        return apply_op(lambda s: jnp.einsum("mk,...mt->...kt", dct, s), lm)
