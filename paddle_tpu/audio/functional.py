"""paddle.audio.functional. Parity: python/paddle/audio/functional/
(functional.py :: hz_to_mel, mel_to_hz, mel_frequencies, fft_frequencies,
compute_fbank_matrix, power_to_db, create_dct; window.py :: get_window).
All pure jnp — XLA fuses the filterbank matmuls onto the MXU."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk: bool = False):
    """Hz → mel. Slaney formula by default (linear <1 kHz, log above), HTK
    formula with htk=True — the reference's dual convention."""
    scalar = not isinstance(freq, (Tensor, jnp.ndarray))
    f = freq._data if isinstance(freq, Tensor) else jnp.asarray(
        freq, jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(f / min_log_hz) / logstep,
                        mels)
    if scalar:
        return float(out)
    return Tensor(out) if isinstance(freq, Tensor) else out


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, (Tensor, jnp.ndarray))
    m = mel._data if isinstance(mel, Tensor) else jnp.asarray(
        mel, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    if scalar:
        return float(out)
    return Tensor(out) if isinstance(mel, Tensor) else out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    """n_mels frequencies evenly spaced on the mel scale."""
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor(mel_to_hz(mels, htk))


def fft_frequencies(sr: int, n_fft: int):
    """Center frequencies of rFFT bins."""
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: float | None = None,
                         htk: bool = False, norm: str = "slaney"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)._data
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)._data
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights)


def power_to_db(magnitude, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: float | None = 80.0):
    """10*log10(S/ref) with amin flooring and optional top_db clipping."""
    def fn(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * jnp.log10(
            jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    if isinstance(magnitude, Tensor):
        return apply_op(fn, magnitude)
    return fn(jnp.asarray(magnitude))


def create_dct(n_mfcc: int, n_mels: int, norm: str | None = "ortho"):
    """DCT-II basis [n_mels, n_mfcc] for MFCC extraction."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    basis = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        scale = jnp.full((n_mfcc,), math.sqrt(2.0 / n_mels))
        scale = scale.at[0].set(math.sqrt(1.0 / n_mels))
        basis = basis * scale[None, :]
    else:
        basis = basis * 2.0
    return Tensor(basis)


def get_window(window: str, win_length: int, fftbins: bool = True):
    """Window function by name (hann/hamming/blackman/bartlett/
    kaiser/gaussian/general_gaussian/exponential/triang/bohman/taylor are the
    reference set; the common core implemented here)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length
    sym = not fftbins
    m = n if sym else n + 1
    t = jnp.arange(m, dtype=jnp.float32)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * t / (m - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * t / (m - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * t / (m - 1))
             + 0.08 * jnp.cos(4 * math.pi * t / (m - 1)))
    elif name == "bartlett":
        w = 1.0 - jnp.abs(2 * t / (m - 1) - 1.0)
    elif name == "triang":
        w = 1.0 - jnp.abs((2 * t - (m - 1)) / (m + (0 if sym else 1) - 1))
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = jnp.i0(beta * jnp.sqrt(
            1 - (2 * t / (m - 1) - 1) ** 2)) / jnp.i0(jnp.asarray(beta))
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = jnp.exp(-0.5 * ((t - (m - 1) / 2) / std) ** 2)
    elif name == "rect" or name == "boxcar":
        w = jnp.ones(m)
    else:
        raise ValueError(f"unsupported window {window!r}")
    if not sym:
        w = w[:-1]
    return Tensor(w)
