"""paddle.audio — audio feature extraction + WAV IO.

Parity: python/paddle/audio/ (functional, features, backends). The soundfile
backend is replaced by a stdlib-`wave` PCM backend (zero extra deps);
load/save cover 16/32-bit PCM WAV, the format the reference's bundled
datasets use."""
from __future__ import annotations

from . import functional
from . import features
from .backends import load, save, info

__all__ = ["functional", "features", "load", "save", "info",
           "backends"]

from . import backends  # noqa: E402
