"""paddle.static IO — inference-program export/import.

Parity: python/paddle/static/io.py :: save_inference_model,
load_inference_model, serialize_program, deserialize_program,
normalize_program, save, load (the reference serializes a ProgramDesc
protobuf + a params file).

TPU-first: the portable program format here is **StableHLO via
jax.export** — the XLA-native equivalent of ProgramDesc. The captured
static Program (op-closure list) is traced once into a pure function
(feeds, params) -> fetches, exported with shape polymorphism for None/-1
feed dims, and written as `{prefix}.pdmodel`; parameter values go to
`{prefix}.pdiparams`. Loading needs no Python model code — the reference's
inference-deployment contract."""
from __future__ import annotations

import json
import re
import struct

import jax
import numpy as np
from jax import export as jax_export

from ..tensor.tensor import Tensor

__all__ = ["save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program", "normalize_program",
           "save", "load"]


def _prune_to_fetches(program, fetch_uids):
    """Backward closure: keep only ops the fetches depend on (the
    reference's prune pass dropping backward/optimizer ops from an
    inference program)."""
    needed = set(fetch_uids)
    kept = []
    for op in reversed(program.ops):
        if any(uid in needed for uid in op.output_ids):
            kept.append(op)
            needed.update(t._uid for t in op.inputs)
    kept.reverse()
    return kept


def normalize_program(program, feed_vars, fetch_vars):
    """Prune to the fetch closure and arrange into (pure_fn, captured):
    pure_fn(feed_arrays, param_arrays) -> fetch arrays."""
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    feed_uids = {t._uid for t in feed_vars}
    ops = _prune_to_fetches(program, [t._uid for t in fetch_vars])
    # captured = inputs of KEPT ops that no kept op produced and aren't feeds
    produced = set()
    captured, seen = [], set()
    for op in ops:
        for t in op.inputs:
            uid = t._uid
            if uid in produced or uid in feed_uids or uid in seen:
                continue
            seen.add(uid)
            captured.append(t)
        produced.update(op.output_ids)
    cap_uids = [t._uid for t in captured]

    def pure_fn(feed_arrays, param_arrays):
        env = dict(zip([t._uid for t in feed_vars], feed_arrays))
        env.update(dict(zip(cap_uids, param_arrays)))
        for op in ops:
            ins = [env.get(t._uid, t._data) for t in op.inputs]
            outs = op.fn(*ins)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for uid, o in zip(op.output_ids, outs):
                env[uid] = o
        return tuple(env[t._uid] for t in fetch_vars)

    return pure_fn, captured, feed_vars, fetch_vars


def _feed_shape_structs(program, feed_vars):
    """ShapeDtypeStructs for export; None/-1 dims become symbolic. Only the
    BATCH axis (axis 0) shares one symbol across feeds (the reference's feed
    contract: every feed carries the same batch size); every other dynamic
    dim gets a per-feed symbol so two feeds with independent dynamic lengths
    at the same axis (encoder [B,Ls] vs decoder [B,Lt]) stay independent."""
    dims_list = []
    any_sym = False
    for fi, t in enumerate(feed_vars):
        name = getattr(t, "name", None)
        spec = program._feed_specs.get(name)
        dims = list(spec.shape if spec is not None else t.shape)
        for i, d in enumerate(dims):
            if d is None or d == -1:
                # feed names like 'fc_0.tmp_2' are not identifiers — keep
                # the symbol name jax_export-legal
                # sanitized name + feed INDEX: two names that sanitize to
                # the same tag ('enc.len'/'enc_len') must not share symbols
                tag = f"f{fi}_" + (re.sub(r"\W", "_", name) if name else "")
                dims[i] = "_b" if i == 0 else f"_{tag}_d{i}"
                any_sym = True
        dims_list.append(dims)
    specs = []
    scope = jax_export.SymbolicScope() if any_sym else None
    sym_cache: dict[str, object] = {}
    for t, dims in zip(feed_vars, dims_list):
        sh = []
        for d in dims:
            if isinstance(d, str):
                if d not in sym_cache:
                    sym_cache[d] = jax_export.symbolic_shape(
                        d, scope=scope)[0]
                sh.append(sym_cache[d])
            else:
                sh.append(d)
        specs.append(jax.ShapeDtypeStruct(tuple(sh), t._data.dtype))
    return specs


class InferenceProgram:
    """A loaded/exported inference program: StableHLO + params. Executor.run
    recognizes it (paddle parity: the object returned in
    load_inference_model's results[0])."""

    def __init__(self, exported_bytes: bytes, feed_names, n_fetch,
                 params):
        self._bytes = exported_bytes
        self._exported = jax_export.deserialize(bytearray(exported_bytes))
        self.feed_names = list(feed_names)
        self.n_fetch = int(n_fetch)
        self.params = [np.asarray(p) for p in params]
        # opaque fetch handles (index markers) for Executor.run parity
        self.fetch_targets = [_FetchHandle(self, i) for i in range(n_fetch)]

    def run_feeds(self, feed: dict):
        arrays = []
        for name in self.feed_names:
            if name not in feed:
                raise KeyError(f"missing feed {name!r}; program feeds are "
                               f"{self.feed_names}")
            v = feed[name]
            arrays.append(np.asarray(v._data if isinstance(v, Tensor)
                                     else v))
        outs = self._exported.call(arrays, self.params)
        return list(outs)


class _FetchHandle:
    __slots__ = ("program", "index")

    def __init__(self, program, index):
        self.program = program
        self.index = index


_MAGIC = b"PTPU1\n"


def _pack(header: dict, blob: bytes) -> bytes:
    """Container: magic + u32 header-len + JSON header + raw StableHLO.
    No pickle — loading a third-party .pdmodel must not execute code (the
    reference's ProgramDesc protobuf has the same property)."""
    h = json.dumps(header).encode()
    return _MAGIC + struct.pack("<I", len(h)) + h + blob


def _unpack(data: bytes):
    if not data.startswith(_MAGIC):
        raise ValueError("not a paddle_tpu .pdmodel file")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    header = json.loads(data[off:off + hlen].decode())
    return header, data[off + hlen:]


def _serialize_normalized(program, pure_fn, captured, feed_vars,
                          fetch_vars) -> bytes:
    feed_structs = _feed_shape_structs(program, feed_vars)
    param_structs = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
                     for t in captured]
    exported = jax_export.export(jax.jit(pure_fn))(feed_structs,
                                                   param_structs)
    header = {
        "feed_names": [getattr(t, "name", None) or f"feed_{i}"
                       for i, t in enumerate(feed_vars)],
        "n_fetch": len(fetch_vars),
    }
    return _pack(header, bytes(exported.serialize()))


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs) -> bytes:
    """Program → portable bytes (StableHLO + JSON feed metadata)."""
    from . import default_main_program
    program = program or default_main_program()
    pure_fn, captured, feed_vars, fetch_vars = normalize_program(
        program, feed_vars, fetch_vars)
    return _serialize_normalized(program, pure_fn, captured, feed_vars,
                                 fetch_vars)


def deserialize_program(data: bytes, params=None) -> InferenceProgram:
    header, blob = _unpack(data)
    return InferenceProgram(blob, header["feed_names"], header["n_fetch"],
                            params or [])


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, program=None, **kwargs):
    """Write {prefix}.pdmodel (serialized program) + {prefix}.pdiparams
    (parameter values in the program's captured order, .npz — no pickle)."""
    from . import default_main_program
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    program = program or default_main_program()
    pure_fn, captured, feed_vars, fetch_vars = normalize_program(
        program, feed_vars, fetch_vars)
    data = _serialize_normalized(program, pure_fn, captured, feed_vars,
                                 fetch_vars)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(data)
    with open(path_prefix + ".pdiparams", "wb") as f:
        np.savez(f, *[np.asarray(t._data) for t in captured])


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """→ [inference_program, feed_target_names, fetch_targets] (reference
    return contract); run via Executor.run(program=..., feed=...,
    fetch_list=fetch_targets)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        data = f.read()
    with np.load(path_prefix + ".pdiparams", allow_pickle=False) as z:
        params = [z[k] for k in z.files]
    prog = deserialize_program(data, params)
    return [prog, prog.feed_names, prog.fetch_targets]


def save(program, model_path: str, protocol: int = 4, **kwargs):
    """paddle.static.save: persist the program's parameters to
    {path}.pdparams (.npz keyed by parameter name — no pickle)."""
    params = {getattr(p, "name", None) or f"param_{i}": np.asarray(p._data)
              for i, p in enumerate(program.all_parameters())}
    with open(model_path + ".pdparams", "wb") as f:
        np.savez(f, **params)


def load(program, model_path: str, executor=None, var_list=None):
    """paddle.static.load: restore parameters saved by static.save into the
    program's persistables (matched by name, else by order)."""
    with np.load(model_path + ".pdparams", allow_pickle=False) as z:
        saved = {k: z[k] for k in z.files}
    params = program.all_parameters()
    by_name = {getattr(p, "name", None): p for p in params}
    import jax.numpy as jnp
    matched = 0
    for i, (name, val) in enumerate(saved.items()):
        target = by_name.get(name)
        if target is None and i < len(params):
            target = params[i]
        if target is not None:
            target._data = jnp.asarray(val)
            matched += 1
    return matched
