"""paddle.static — Program/Executor facade over jitted execution.

Parity: python/paddle/static/ (Program, program_guard, Executor,
InterpreterCore at paddle/fluid/framework/new_executor/). TPU-first: a
"Program" records a traced callable; the Executor jit-compiles and runs it —
XLA plays the roles of ProgramDesc (graph), dependency analysis and stream
scheduling, so there is no instruction-list interpreter to rebuild.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "CompiledProgram",
           "InputSpec", "data", "name_scope", "global_scope", "Scope"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class Program:
    """Recorded computation: a list of (fn, feeds, fetch) built eagerly.

    The reference's ProgramDesc is a protobuf op graph; here the program body
    is the traced Python callable itself (XLA's jaxpr is the graph).
    """

    def __init__(self):
        self._build_fn = None
        self._feed_names: list[str] = []
        self._fetch: list = []
        self.random_seed = 0

    def clone(self, for_test=False):
        p = Program()
        p._build_fn = self._build_fn
        p._feed_names = list(self._feed_names)
        p._fetch = list(self._fetch)
        return p

    def global_block(self):
        return self

    def all_parameters(self):
        from ..tensor.tensor import persistent_tensors, Parameter
        return [t for t in persistent_tensors() if isinstance(t, Parameter)]


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    spec = InputSpec(shape, dtype, name)
    _main_program._feed_names.append(name)
    return spec


@contextlib.contextmanager
def name_scope(prefix):
    yield


class Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


class Executor:
    """paddle.static.Executor parity: run(program, feed, fetch_list)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        program = program or default_main_program()
        if callable(getattr(program, "_build_fn", None)):
            feed = feed or {}
            feed_tensors = {k: (v if isinstance(v, Tensor) else Tensor(np.asarray(v)))
                            for k, v in feed.items()}
            outs = program._build_fn(**feed_tensors)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            if return_numpy:
                return [o.numpy() if isinstance(o, Tensor) else o for o in outs]
            return list(outs)
        return []

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
