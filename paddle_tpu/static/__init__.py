"""paddle.static — Program/Executor with real graph capture + replay.

Parity: python/paddle/static/ (Program, program_guard, data, Executor;
the executing engine being paddle/fluid/framework/new_executor/ ::
InterpreterCore). TPU-first: while static mode is on, every op executed
through the tensor facade is ALSO recorded into the active Program as a
(pure-fn, inputs, outputs) triple; `Executor.run(program, feed, fetch_list)`
replays the recorded graph with the feeds substituted — the replay is the
reference's instruction-list interpretation, except each "instruction" is a
pure jnp closure and XLA performs the dependency analysis/scheduling when
the replay is jitted. `Optimizer.minimize(loss)` captured during build
re-runs backward+update on the replayed values each `run`, which is exactly
the reference's appended backward+optimizer ops.

Canonical flow (same code as the reference):
    paddle.enable_static()
    x = paddle.static.data("x", [None, 13])
    y = model(x)                       # ops recorded into main program
    loss = F.mse_loss(y, label); opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    out, = exe.run(feed={"x": arr, ...}, fetch_list=[loss])
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from ..tensor.tensor import Tensor, _capture_hook, no_grad

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "CompiledProgram",
           "InputSpec", "data", "name_scope", "global_scope", "Scope",
           "save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program", "normalize_program",
           "save", "load"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class _OpRecord:
    __slots__ = ("fn", "inputs", "output_ids")

    def __init__(self, fn, inputs, output_ids):
        self.fn = fn                # pure jnp closure
        self.inputs = inputs        # list[Tensor] (live refs; params see
        #                             their CURRENT values at replay)
        self.output_ids = output_ids


class _RecomputeSegment(_OpRecord):
    """A run of recorded ops replayed as ONE tape node (fleet recompute).

    Built by the static recompute pass (fleet/meta_optimizers/static_meta).
    inputs = boundary tensors consumed from outside the segment; output_ids
    = the produced uids that later ops (or the loss) still need. During a
    training replay the whole segment goes through fleet's ``recompute`` so
    only boundaries stay live; backward re-runs the inner ops.
    """

    __slots__ = ("inner_ops",)

    def __init__(self, inner_ops, inputs, output_ids):
        super().__init__(None, inputs, output_ids)
        self.inner_ops = inner_ops

    def replay(self, ins, training):
        from ..tensor.tensor import apply_op

        def seg_fn(*boundary):
            local = {t._uid: v for t, v in zip(self.inputs, boundary)}
            for iop in self.inner_ops:
                iins = [local.get(t._uid, t) for t in iop.inputs]
                iouts = apply_op(iop.fn, *iins)
                iouts = iouts if isinstance(iouts, tuple) else (iouts,)
                for uid, o in zip(iop.output_ids, iouts):
                    local[uid] = o
            return tuple(local[u] for u in self.output_ids)

        if training:
            from ..distributed.fleet.utils.recompute_mod import recompute
            outs = recompute(seg_fn, *ins)
        else:
            with no_grad():
                outs = seg_fn(*ins)
        return outs if isinstance(outs, tuple) else (outs,)


class Program:
    """Recorded op graph (the reference's ProgramDesc, with jnp closures as
    the op bodies)."""

    def __init__(self):
        self.ops: list[_OpRecord] = []
        self.feed_holders: dict[int, str] = {}   # tensor uid -> feed name
        self._feed_specs: dict[str, InputSpec] = {}
        self._feeds_requiring_grad: set = set()  # names (static gradients())
        self._minimize_hooks: list = []          # (optimizer, loss_uid)
        self.random_seed = 0

    # ----------------------------------------------------------- build
    def _record(self, fn, inputs, outputs):
        self.ops.append(_OpRecord(fn, list(inputs),
                                  [o._uid for o in outputs]))

    def _add_feed(self, name, spec, placeholder):
        self.feed_holders[placeholder._uid] = name
        self._feed_specs[name] = spec

    def _add_minimize(self, optimizer, loss):
        self._minimize_hooks.append((optimizer, loss._uid))

    # ----------------------------------------------------------- API parity
    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.feed_holders = dict(self.feed_holders)
        p._feed_specs = dict(self._feed_specs)
        p._feeds_requiring_grad = set(self._feeds_requiring_grad)
        if not for_test:
            p._minimize_hooks = list(self._minimize_hooks)
        return p

    def global_block(self):
        return self

    def all_parameters(self):
        from ..tensor.tensor import persistent_tensors, Parameter
        return [t for t in persistent_tensors() if isinstance(t, Parameter)]

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, "
                f"feeds={list(self._feed_specs)}, "
                f"minimize={len(self._minimize_hooks)})")


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def _alias_capture_output(src: Tensor, dst: Tensor) -> None:
    """Rewrite the last recorded op's output uid from ``src`` to ``dst``.

    Tensor.__setitem__ during static capture records the scatter as an op
    producing a fresh tensor; aliasing its output uid onto the assigned
    tensor's uid makes replay treat it as an in-place update (later ops
    that consume the target tensor read the scattered value from env)."""
    ops = _main_program.ops
    if ops and src._uid in ops[-1].output_ids:
        ids = ops[-1].output_ids
        ids[ids.index(src._uid)] = dst._uid


def _install_capture():
    """Called by paddle.enable_static(): record ops into the active main
    program. paddle.disable_static() removes the hook."""
    def hook(fn, inputs, outputs):
        _main_program._record(fn, inputs, outputs)
    _capture_hook[0] = hook


def _remove_capture():
    _capture_hook[0] = None


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    # re-point the capture hook at the new main program
    if _capture_hook[0] is not None:
        _install_capture()
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s
        if _capture_hook[0] is not None:
            _install_capture()


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder: returns a Tensor of zeros (shape with None/-1 dims
    filled as 1 for the build pass) registered as a feed target."""
    spec = InputSpec(shape, dtype, name)
    build_shape = [1 if (s is None or s == -1) else s for s in spec.shape]
    t = Tensor(np.zeros(build_shape, dtype=np.dtype(dtype)),
               stop_gradient=True)
    t.name = name
    _main_program._add_feed(name, spec, t)
    return t


@contextlib.contextmanager
def name_scope(prefix):
    yield


class Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


class Executor:
    """Replay engine. Parity: paddle.static.Executor / InterpreterCore."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        from .io import InferenceProgram, _FetchHandle
        if isinstance(program, InferenceProgram):
            outs = program.run_feeds(feed or {})
            picked = []
            for f in (fetch_list or program.fetch_targets):
                idx = f.index if isinstance(f, _FetchHandle) else int(f)
                o = outs[idx]
                picked.append(np.asarray(o) if return_numpy else Tensor(o))
            return picked
        data_parallel = isinstance(program, CompiledProgram) and \
            getattr(program, "_data_parallel", False)
        program = program if isinstance(program, Program) else \
            (program.program if isinstance(program, CompiledProgram)
             else None) or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []

        # replay must not re-capture
        saved_hook = _capture_hook[0]
        _capture_hook[0] = None
        try:
            env: dict[int, Tensor] = {}
            for uid, name in program.feed_holders.items():
                if name in feed:
                    v = feed[name]
                    t = v if isinstance(v, Tensor) else \
                        Tensor(np.asarray(v))
                    if name in program._feeds_requiring_grad:
                        if t is v:
                            # never mutate a caller's Tensor permanently:
                            # wrap its array in a fresh run-local Tensor
                            t = Tensor(v._data)
                        t.stop_gradient = False
                    if data_parallel:
                        # static-dp pass: shard the feed's batch dim over
                        # the hybrid mesh's data axes (the reference's
                        # distributed-program rewrite feeds per-rank
                        # slices; GSPMD runs the replayed ops SPMD)
                        from ..parallel import shard_batch
                        t = shard_batch(t)
                    env[uid] = t
            from ..tensor.tensor import apply_op
            training = bool(program._minimize_hooks)
            for op in program.ops:
                ins = [env.get(t._uid, t) for t in op.inputs]
                if isinstance(op, _RecomputeSegment):
                    outs = op.replay(ins, training)
                elif training:
                    outs = apply_op(op.fn, *ins)
                else:
                    with no_grad():
                        outs = apply_op(op.fn, *ins)
                outs = outs if isinstance(outs, tuple) else (outs,)
                for uid, o in zip(op.output_ids, outs):
                    env[uid] = o
            for optimizer, loss_uid in program._minimize_hooks:
                loss = env.get(loss_uid)
                if loss is not None:
                    if hasattr(optimizer, "_static_apply"):
                        # meta-optimizer stack (amp scaling, gradient
                        # merge, sharding) drives its own backward+update
                        optimizer._static_apply(loss)
                    else:
                        loss.backward()
                        optimizer.step()
                        post = getattr(optimizer, "_post_run", None)
                        if post is not None:
                            post(env)   # bind @GRAD handles before clear
                        optimizer.clear_grad()
            results = []
            pruned = None
            for f in fetch_list:
                uid = f._uid if isinstance(f, Tensor) else None
                if uid is not None and uid not in env:
                    # the env fallback below rightly serves live refs
                    # (params, captured constants) — but an op OUTPUT
                    # missing from env was recompute-pruned, and silently
                    # returning its stale capture-time value is wrong data
                    if pruned is None:
                        pruned = set()
                        for op in program.ops:
                            if isinstance(op, _RecomputeSegment):
                                inner = {u for i in op.inner_ops
                                         for u in i.output_ids}
                                pruned |= inner - set(op.output_ids)
                    if uid in pruned:
                        raise RuntimeError(
                            f"fetch target {getattr(f, 'name', uid)!r} is "
                            "an intermediate inside a recompute segment "
                            "and was freed; fetch checkpoint/boundary "
                            "variables or disable strategy.recompute")
                out = env.get(uid, f if isinstance(f, Tensor) else None)
                if out is None:
                    results.append(None)
                elif return_numpy:
                    results.append(np.asarray(out._data))
                else:
                    results.append(out)
            return results
        finally:
            _capture_hook[0] = saved_hook

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy
        self._data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Parity: CompiledProgram.with_data_parallel — marks the program
        for data-parallel execution; Executor.run then shards feeds over
        the active hybrid mesh's data axes (fleet.init supplies the mesh)."""
        self._data_parallel = True
        return self


# inference-program IO (module kept separate: paddle.static.io parity)
from .io import (save_inference_model, load_inference_model,  # noqa: E402
                 serialize_program, deserialize_program, normalize_program,
                 save, load)
from . import io  # noqa: E402


# ---------------------------------------------------------------------------
# Legacy static-graph API additions (r3): append_backward / gradients /
# scope_guard / places / device_guard / program state / EMA / py_func
# ---------------------------------------------------------------------------

class _BackwardHook:
    """Minimize-hook shaped object that ONLY runs the backward (reference
    append_backward: grads are materialized, updates are the caller's
    business). Grad handles registered here are bound into the run env
    by _post_run so they can be fetched."""

    def __init__(self, pairs):
        self._pairs = pairs        # [(param, grad_handle)]

    def step(self):
        pass

    def clear_grad(self):
        pass                            # clearing happens in _post_run,
        #                                 which can resolve the LIVE tensor

    def _post_run(self, env):
        # bind then clear on the RUN-time tensor (env holds fed tensors;
        # params resolve to themselves) — without the clear, grads would
        # ACCUMULATE across Executor.run calls (backward is +=)
        for p, gh in self._pairs:
            live = env.get(p._uid, p)
            if live.grad is not None:
                env[gh._uid] = live.grad
            live.grad = None


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Register backward for `loss` in the active Program; returns
    [(param, grad_handle)] — fetch a grad_handle from Executor.run to
    read the gradient (reference: paddle.static.append_backward)."""
    prog = default_main_program()
    params = list(parameter_list) if parameter_list else \
        prog.all_parameters()
    pairs = []
    for p in params:
        gh = Tensor(np.zeros((), np.float32))
        gh.name = (getattr(p, "name", None) or "param") + "@GRAD"
        pairs.append((p, gh))
    hook = _BackwardHook(pairs)
    prog._minimize_hooks.append((hook, loss._uid))
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static d(sum(targets))/d(inputs) handles (reference:
    paddle.static.gradients); realized through the same backward hook —
    inputs must require grad (stop_gradient=False). target_gradients
    (custom output cotangents) are not supported — raise loudly rather
    than silently differentiating the unweighted sum."""
    if target_gradients is not None:
        raise NotImplementedError(
            "static.gradients target_gradients is not supported; weight "
            "the targets before calling (loss = sum(w_i * y_i))")
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if no_grad_set:
        ban = {id(t) for t in no_grad_set}
        inputs = [x for x in inputs if id(x) not in ban]
    prog = default_main_program()
    pairs = []
    for x in inputs:
        gh = Tensor(np.zeros((), np.float32))
        gh.name = (getattr(x, "name", None) or "x") + "@GRAD"
        pairs.append((x, gh))
        fname = prog.feed_holders.get(x._uid)
        if fname is not None:   # feed input: the RUN-time tensor must
            prog._feeds_requiring_grad.add(fname)   # require grad
    # ONE hook on the summed target: backward() clears the tape when it
    # finishes, so per-target hooks would leave every target after the
    # first with nothing to differentiate
    if len(targets) == 1:
        loss_t = targets[0]
    else:
        from ..tensor.math import add_n
        loss_t = add_n([t.sum() for t in targets])
    prog._minimize_hooks.append((_BackwardHook(pairs), loss_t._uid))
    return [gh for _, gh in pairs]


@contextlib.contextmanager
def scope_guard(scope):
    """Bind `scope` as the global scope within the context (reference:
    paddle.static.scope_guard)."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    import os
    n = device_count or int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (the reference's GPU places = TPU chips here)."""
    from ..core.place import CUDAPlace
    if device_ids is None:
        import jax
        device_ids = range(len(jax.devices()))
    return [CUDAPlace(i) for i in device_ids]


@contextlib.contextmanager
def device_guard(device=None):
    """Op-placement guard. XLA owns placement on TPU; the guard is accepted
    for parity and is a no-op (documented deviation)."""
    yield


def set_program_state(program, state_dict):
    """Write a {name: ndarray} state into the program's parameters."""
    import numpy as _np
    by_name = {getattr(p, "name", None): p
               for p in program.all_parameters()}
    for k, v in state_dict.items():
        p = by_name.get(k)
        if p is not None:
            arr = v.numpy() if hasattr(v, "numpy") else _np.asarray(v)
            import jax.numpy as jnp
            p._data = jnp.asarray(arr, p._data.dtype)


class ExponentialMovingAverage:
    """EMA of parameters with decay (+ optional Adam-style bias-correction
    via thres_steps ignored); apply()/restore() swap windows (reference:
    paddle.static.ExponentialMovingAverage)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._ema = {}
        self._backup = None
        self._params = None
        self._step = 0

    def _ensure(self):
        if self._params is None:
            self._params = default_main_program().all_parameters()
            import jax.numpy as jnp
            for p in self._params:
                # zero-init + bias correction in apply() (the reference's
                # scheme); seeding with the live value AND dividing by
                # 1-decay^t would double-count
                self._ema[p._uid] = jnp.zeros_like(p._data, jnp.float32)

    def update(self):
        import jax.numpy as jnp
        self._ensure()
        self._step += 1
        d = self.decay
        for p in self._params:
            self._ema[p._uid] = d * self._ema[p._uid] + \
                (1 - d) * p._data.astype(jnp.float32)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._ensure()
        if self._step == 0:
            # nothing accumulated: applying would zero every parameter
            yield
            return
        self._backup = {p._uid: p._data for p in self._params}
        bias = 1.0 - self.decay ** max(self._step, 1)
        for p in self._params:
            p._data = (self._ema[p._uid] / bias).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            if p._uid in self._backup:
                p._data = self._backup[p._uid]
        self._backup = None


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Wrap a host-side python function as an op (reference:
    paddle.static.py_func). Lowered via jax.pure_callback so the call
    survives jit/Program replay; `out` provides the result template
    (shape/dtype). backward_func is not supported (raise if given)."""
    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func is not supported on the TPU build; "
            "define a custom op via paddle.autograd.PyLayer instead")
    import jax
    import jax.numpy as jnp
    from ..tensor.tensor import apply_op
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
                 for o in outs]

    def fn(*arrs):
        def host(*hs):
            res = func(*hs)
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r) for r in res)
        res = jax.pure_callback(host, tuple(templates), *arrs)
        return res if len(res) > 1 else res[0]
    result = apply_op(fn, *xs)
    results = result if isinstance(result, tuple) else (result,)
    for o, r in zip(outs, results):
        o._data = r._data
        _alias_capture_output(r, o)   # replay binds the result to `out`
    return out


from . import nn  # noqa: E402

__all__ += ["append_backward", "gradients", "scope_guard", "cpu_places",
            "cuda_places", "device_guard", "set_program_state",
            "ExponentialMovingAverage", "py_func", "nn"]
