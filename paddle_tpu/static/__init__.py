"""paddle.static — Program/Executor with real graph capture + replay.

Parity: python/paddle/static/ (Program, program_guard, data, Executor;
the executing engine being paddle/fluid/framework/new_executor/ ::
InterpreterCore). TPU-first: while static mode is on, every op executed
through the tensor facade is ALSO recorded into the active Program as a
(pure-fn, inputs, outputs) triple; `Executor.run(program, feed, fetch_list)`
replays the recorded graph with the feeds substituted — the replay is the
reference's instruction-list interpretation, except each "instruction" is a
pure jnp closure and XLA performs the dependency analysis/scheduling when
the replay is jitted. `Optimizer.minimize(loss)` captured during build
re-runs backward+update on the replayed values each `run`, which is exactly
the reference's appended backward+optimizer ops.

Canonical flow (same code as the reference):
    paddle.enable_static()
    x = paddle.static.data("x", [None, 13])
    y = model(x)                       # ops recorded into main program
    loss = F.mse_loss(y, label); opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    out, = exe.run(feed={"x": arr, ...}, fetch_list=[loss])
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np

from ..tensor.tensor import Tensor, _capture_hook, no_grad

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "CompiledProgram",
           "InputSpec", "data", "name_scope", "global_scope", "Scope",
           "save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program", "normalize_program",
           "save", "load"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class _OpRecord:
    __slots__ = ("fn", "inputs", "output_ids")

    def __init__(self, fn, inputs, output_ids):
        self.fn = fn                # pure jnp closure
        self.inputs = inputs        # list[Tensor] (live refs; params see
        #                             their CURRENT values at replay)
        self.output_ids = output_ids


class _RecomputeSegment(_OpRecord):
    """A run of recorded ops replayed as ONE tape node (fleet recompute).

    Built by the static recompute pass (fleet/meta_optimizers/static_meta).
    inputs = boundary tensors consumed from outside the segment; output_ids
    = the produced uids that later ops (or the loss) still need. During a
    training replay the whole segment goes through fleet's ``recompute`` so
    only boundaries stay live; backward re-runs the inner ops.
    """

    __slots__ = ("inner_ops",)

    def __init__(self, inner_ops, inputs, output_ids):
        super().__init__(None, inputs, output_ids)
        self.inner_ops = inner_ops

    def replay(self, ins, training):
        from ..tensor.tensor import apply_op

        def seg_fn(*boundary):
            local = {t._uid: v for t, v in zip(self.inputs, boundary)}
            for iop in self.inner_ops:
                iins = [local.get(t._uid, t) for t in iop.inputs]
                iouts = apply_op(iop.fn, *iins)
                iouts = iouts if isinstance(iouts, tuple) else (iouts,)
                for uid, o in zip(iop.output_ids, iouts):
                    local[uid] = o
            return tuple(local[u] for u in self.output_ids)

        if training:
            from ..distributed.fleet.utils.recompute_mod import recompute
            outs = recompute(seg_fn, *ins)
        else:
            with no_grad():
                outs = seg_fn(*ins)
        return outs if isinstance(outs, tuple) else (outs,)


class Program:
    """Recorded op graph (the reference's ProgramDesc, with jnp closures as
    the op bodies)."""

    def __init__(self):
        self.ops: list[_OpRecord] = []
        self.feed_holders: dict[int, str] = {}   # tensor uid -> feed name
        self._feed_specs: dict[str, InputSpec] = {}
        self._minimize_hooks: list = []          # (optimizer, loss_uid)
        self.random_seed = 0

    # ----------------------------------------------------------- build
    def _record(self, fn, inputs, outputs):
        self.ops.append(_OpRecord(fn, list(inputs),
                                  [o._uid for o in outputs]))

    def _add_feed(self, name, spec, placeholder):
        self.feed_holders[placeholder._uid] = name
        self._feed_specs[name] = spec

    def _add_minimize(self, optimizer, loss):
        self._minimize_hooks.append((optimizer, loss._uid))

    # ----------------------------------------------------------- API parity
    def clone(self, for_test=False):
        p = Program()
        p.ops = list(self.ops)
        p.feed_holders = dict(self.feed_holders)
        p._feed_specs = dict(self._feed_specs)
        if not for_test:
            p._minimize_hooks = list(self._minimize_hooks)
        return p

    def global_block(self):
        return self

    def all_parameters(self):
        from ..tensor.tensor import persistent_tensors, Parameter
        return [t for t in persistent_tensors() if isinstance(t, Parameter)]

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, "
                f"feeds={list(self._feed_specs)}, "
                f"minimize={len(self._minimize_hooks)})")


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def _alias_capture_output(src: Tensor, dst: Tensor) -> None:
    """Rewrite the last recorded op's output uid from ``src`` to ``dst``.

    Tensor.__setitem__ during static capture records the scatter as an op
    producing a fresh tensor; aliasing its output uid onto the assigned
    tensor's uid makes replay treat it as an in-place update (later ops
    that consume the target tensor read the scattered value from env)."""
    ops = _main_program.ops
    if ops and src._uid in ops[-1].output_ids:
        ids = ops[-1].output_ids
        ids[ids.index(src._uid)] = dst._uid


def _install_capture():
    """Called by paddle.enable_static(): record ops into the active main
    program. paddle.disable_static() removes the hook."""
    def hook(fn, inputs, outputs):
        _main_program._record(fn, inputs, outputs)
    _capture_hook[0] = hook


def _remove_capture():
    _capture_hook[0] = None


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    # re-point the capture hook at the new main program
    if _capture_hook[0] is not None:
        _install_capture()
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s
        if _capture_hook[0] is not None:
            _install_capture()


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder: returns a Tensor of zeros (shape with None/-1 dims
    filled as 1 for the build pass) registered as a feed target."""
    spec = InputSpec(shape, dtype, name)
    build_shape = [1 if (s is None or s == -1) else s for s in spec.shape]
    t = Tensor(np.zeros(build_shape, dtype=np.dtype(dtype)),
               stop_gradient=True)
    t.name = name
    _main_program._add_feed(name, spec, t)
    return t


@contextlib.contextmanager
def name_scope(prefix):
    yield


class Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


class Executor:
    """Replay engine. Parity: paddle.static.Executor / InterpreterCore."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        from .io import InferenceProgram, _FetchHandle
        if isinstance(program, InferenceProgram):
            outs = program.run_feeds(feed or {})
            picked = []
            for f in (fetch_list or program.fetch_targets):
                idx = f.index if isinstance(f, _FetchHandle) else int(f)
                o = outs[idx]
                picked.append(np.asarray(o) if return_numpy else Tensor(o))
            return picked
        data_parallel = isinstance(program, CompiledProgram) and \
            getattr(program, "_data_parallel", False)
        program = program if isinstance(program, Program) else \
            (program.program if isinstance(program, CompiledProgram)
             else None) or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []

        # replay must not re-capture
        saved_hook = _capture_hook[0]
        _capture_hook[0] = None
        try:
            env: dict[int, Tensor] = {}
            for uid, name in program.feed_holders.items():
                if name in feed:
                    v = feed[name]
                    t = v if isinstance(v, Tensor) else \
                        Tensor(np.asarray(v))
                    if data_parallel:
                        # static-dp pass: shard the feed's batch dim over
                        # the hybrid mesh's data axes (the reference's
                        # distributed-program rewrite feeds per-rank
                        # slices; GSPMD runs the replayed ops SPMD)
                        from ..parallel import shard_batch
                        t = shard_batch(t)
                    env[uid] = t
            from ..tensor.tensor import apply_op
            training = bool(program._minimize_hooks)
            for op in program.ops:
                ins = [env.get(t._uid, t) for t in op.inputs]
                if isinstance(op, _RecomputeSegment):
                    outs = op.replay(ins, training)
                elif training:
                    outs = apply_op(op.fn, *ins)
                else:
                    with no_grad():
                        outs = apply_op(op.fn, *ins)
                outs = outs if isinstance(outs, tuple) else (outs,)
                for uid, o in zip(op.output_ids, outs):
                    env[uid] = o
            for optimizer, loss_uid in program._minimize_hooks:
                loss = env.get(loss_uid)
                if loss is not None:
                    if hasattr(optimizer, "_static_apply"):
                        # meta-optimizer stack (amp scaling, gradient
                        # merge, sharding) drives its own backward+update
                        optimizer._static_apply(loss)
                    else:
                        loss.backward()
                        optimizer.step()
                        optimizer.clear_grad()
            results = []
            pruned = None
            for f in fetch_list:
                uid = f._uid if isinstance(f, Tensor) else None
                if uid is not None and uid not in env:
                    # the env fallback below rightly serves live refs
                    # (params, captured constants) — but an op OUTPUT
                    # missing from env was recompute-pruned, and silently
                    # returning its stale capture-time value is wrong data
                    if pruned is None:
                        pruned = set()
                        for op in program.ops:
                            if isinstance(op, _RecomputeSegment):
                                inner = {u for i in op.inner_ops
                                         for u in i.output_ids}
                                pruned |= inner - set(op.output_ids)
                    if uid in pruned:
                        raise RuntimeError(
                            f"fetch target {getattr(f, 'name', uid)!r} is "
                            "an intermediate inside a recompute segment "
                            "and was freed; fetch checkpoint/boundary "
                            "variables or disable strategy.recompute")
                out = env.get(uid, f if isinstance(f, Tensor) else None)
                if out is None:
                    results.append(None)
                elif return_numpy:
                    results.append(np.asarray(out._data))
                else:
                    results.append(out)
            return results
        finally:
            _capture_hook[0] = saved_hook

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy
        self._data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Parity: CompiledProgram.with_data_parallel — marks the program
        for data-parallel execution; Executor.run then shards feeds over
        the active hybrid mesh's data axes (fleet.init supplies the mesh)."""
        self._data_parallel = True
        return self


# inference-program IO (module kept separate: paddle.static.io parity)
from .io import (save_inference_model, load_inference_model,  # noqa: E402
                 serialize_program, deserialize_program, normalize_program,
                 save, load)
from . import io  # noqa: E402
