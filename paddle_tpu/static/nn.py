"""paddle.static.nn — legacy static-graph layer builders.

Parity: python/paddle/static/nn/common.py (fc, conv2d, batch_norm,
layer_norm, embedding, ...) — the 1.x-style functions that CREATE
parameters on call and record ops into the active Program. Here each
builder instantiates the corresponding nn.Layer (parameter registration
rides the persistent registry) and applies it, so the op records into the
Program capture exactly like dygraph layers under program_guard.
"""
from __future__ import annotations

import numpy as np

__all__ = ["fc", "embedding", "conv2d", "conv3d", "batch_norm",
           "layer_norm", "dropout", "conv2d_transpose", "prelu"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..nn.layer.common import Linear
    from ..tensor.tensor import apply_op
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if num_flatten_dims != len(x.shape) - 1 or in_dim != x.shape[-1]:
        # flatten trailing dims with a shape computed FROM THE ARRAY at
        # replay time — reshape() would bake the capture-time batch (the
        # None placeholder dim materializes as 1) into the recorded op
        k = num_flatten_dims
        x = apply_op(lambda a: a.reshape(a.shape[:k] + (-1,)), x)
    lin = Linear(in_dim, size, weight_attr=weight_attr,
                 bias_attr=bias_attr)
    out = lin(x)
    if activation:
        from ..nn import functional as F
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from ..nn.layer.common import Embedding
    emb = Embedding(size[0], size[1], padding_idx=padding_idx,
                    weight_attr=param_attr)
    return emb(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    from ..nn.layer.conv import Conv2D
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    conv = Conv2D(in_ch, num_filters, filter_size, stride=stride,
                  padding=padding, dilation=dilation, groups=groups,
                  weight_attr=param_attr, bias_attr=bias_attr,
                  data_format=data_format)
    out = conv(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    from ..nn.layer.conv import Conv3D
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    conv = Conv3D(in_ch, num_filters, filter_size, stride=stride,
                  padding=padding, dilation=dilation, groups=groups,
                  weight_attr=param_attr, bias_attr=bias_attr,
                  data_format=data_format)
    out = conv(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    from ..nn.layer.conv import Conv2DTranspose
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    conv = Conv2DTranspose(in_ch, num_filters, filter_size, stride=stride,
                           padding=padding, dilation=dilation,
                           groups=groups, weight_attr=param_attr,
                           bias_attr=bias_attr, data_format=data_format)
    if output_size is not None:
        raise NotImplementedError(
            "static.nn.conv2d_transpose output_size is not supported; "
            "size the transpose via filter_size/stride/padding")
    out = conv(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None):
    from ..nn.layer.norm import BatchNorm
    bn = BatchNorm(input.shape[1] if data_layout == "NCHW"
                   else input.shape[-1], momentum=momentum,
                   epsilon=epsilon, data_format=data_layout)
    if is_test:
        bn.eval()
    out = bn(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn.layer.norm import LayerNorm
    shape = list(input.shape[begin_norm_axis:])
    ln = LayerNorm(shape, epsilon=epsilon)
    out = ln(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None):
    from ..nn import functional as F
    return F.dropout(x, p=dropout_prob, training=not is_test)


def prelu(x, mode="all", param_attr=None, name=None):
    from ..nn.layer.activation import PReLU
    num = 1 if mode == "all" else (x.shape[1] if mode == "channel" else
                                   int(np.prod(x.shape[1:])))
    return PReLU(num_parameters=num)(x)
