"""Gateway-driven autoscaling: spawn/drain replicas on telemetry
signals.

The ``Autoscaler`` closes the loop the trace plane opened: the router
already polls every replica's ``telemetry_snapshot()`` (schema v3) for
placement — this consumer reads the SAME payload for capacity
decisions:

  * queue pressure   — mean ``queue_depth`` across placeable replicas
    (the backlog the SLO layer attributes to queueing);
  * pool headroom    — the minimum ``kv_blocks_free / kv_blocks_total``
    across replicas (a full pool sheds admissions before queues grow);
  * goodput verdicts — new ``slo.violated_queue`` counts since the
    last tick (a request that already missed its objective because it
    queued too long is the lagging-edge scale-up signal).

Decisions are deliberately sluggish: a watermark must hold for
``hysteresis`` consecutive ticks before acting, and ``cooldown_s``
must elapse after any scale event before the next — flapping load
changes the replica set at most once per cooldown instead of
thrashing spawn/drain cycles.

Scale-UP calls the caller-provided ``spawn(name)`` hook (build an
engine, wrap it in a replica handle, return it) and registers the
result via ``Router.add_replica`` — consistent hashing moves only the
new replica's keys. In-process clusters spawn ``LocalReplica``s; an
out-of-process deployment spawns a worker under the PR-3 gang
supervisor (``distributed/launch``), calls ``serve_engine()`` in it,
and returns an ``RpcReplica`` — the heartbeat/liveness machinery is
the same either way.

Scale-DOWN picks the least-loaded replica (fewest sessions to move)
and calls ``Router.remove_replica(..., migrate=True)``: live sessions
migrate off (KV blocks + sampler state — zero re-prefill, greedy
token-identical), then the replica retires. A drain that cannot place
a session falls back to classic failover per session; the stream is
never dropped.

Knobs (constructor args override env; registered in
``paddle_tpu.testing.GW_ENV_VARS``):

  PADDLE_AUTOSCALE_MIN          floor replica count (1)
  PADDLE_AUTOSCALE_MAX          ceiling replica count (4)
  PADDLE_AUTOSCALE_QUEUE_HIGH   mean queue depth tripping scale-up (4.0)
  PADDLE_AUTOSCALE_QUEUE_LOW    mean queue depth allowing scale-down (0.5)
  PADDLE_AUTOSCALE_KV_FREE_FRAC min pool-free fraction below which the
                                cluster scales up (0.1)
  PADDLE_AUTOSCALE_COOLDOWN_S   seconds between scale events (10)
  PADDLE_AUTOSCALE_HYSTERESIS   consecutive agreeing ticks required (2)

Disaggregated mode (``role_aware=True`` / PADDLE_AUTOSCALE_ROLE_AWARE):
the PREFILL pool and the DECODE pool scale on DIFFERENT signal
families — prefill work is arrival-shaped (queue depth is the load),
decode work is residency-shaped (live sessions pinning KV). One
global watermark would starve whichever pool's signal is quieter.
The spawn hook must accept ``spawn(name, role)`` in this mode, and
each pool keeps at least one replica regardless of watermarks.

  PADDLE_AUTOSCALE_ROLE_AWARE      enable per-pool scaling (0)
  PADDLE_AUTOSCALE_PF_QUEUE_HIGH   prefill-pool mean queue depth
                                   tripping scale-up (queue_high)
  PADDLE_AUTOSCALE_PF_QUEUE_LOW    prefill-pool mean queue depth
                                   allowing scale-down (queue_low)
  PADDLE_AUTOSCALE_DC_KV_FREE_FRAC decode-pool min free-block fraction
                                   below which it scales up
                                   (kv_free_low)
  PADDLE_AUTOSCALE_DC_SESSIONS_HIGH decode-pool worst resident-session
                                   fraction tripping scale-up (0.85)
  PADDLE_AUTOSCALE_DC_SESSIONS_LOW  decode-pool worst resident-session
                                   fraction allowing scale-down (0.3)
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["Autoscaler"]


def _env(name, default, cast):
    v = os.environ.get(name)
    return cast(v) if v not in (None, "") else default


class Autoscaler:
    """See the module docstring. ``tick()`` is the whole control loop:
    the gateway's health sweep calls it (or a bench/test drives it
    explicitly on a virtual clock); it reads signals, applies
    hysteresis + cooldown, and performs at most ONE scale event."""

    def __init__(self, router, spawn, min_replicas=None,
                 max_replicas=None, queue_high=None, queue_low=None,
                 kv_free_low=None, cooldown_s=None, hysteresis=None,
                 clock=None, name_prefix="scaled", role_aware=None,
                 pf_queue_high=None, pf_queue_low=None,
                 dc_kv_free_low=None, dc_sessions_high=None,
                 dc_sessions_low=None):
        self.router = router
        self.spawn = spawn
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env("PADDLE_AUTOSCALE_MIN", 1, int))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env("PADDLE_AUTOSCALE_MAX", 4, int))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min ({self.min_replicas}) <= max "
                f"({self.max_replicas}) replicas")
        self.queue_high = float(
            queue_high if queue_high is not None
            else _env("PADDLE_AUTOSCALE_QUEUE_HIGH", 4.0, float))
        self.queue_low = float(
            queue_low if queue_low is not None
            else _env("PADDLE_AUTOSCALE_QUEUE_LOW", 0.5, float))
        if not 0 <= self.queue_low < self.queue_high:
            raise ValueError(
                f"need 0 <= queue_low ({self.queue_low}) < queue_high "
                f"({self.queue_high}) — equal watermarks flap on every "
                "tick")
        self.kv_free_low = float(
            kv_free_low if kv_free_low is not None
            else _env("PADDLE_AUTOSCALE_KV_FREE_FRAC", 0.1, float))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else _env("PADDLE_AUTOSCALE_COOLDOWN_S", 10.0, float))
        self.hysteresis = int(
            hysteresis if hysteresis is not None
            else _env("PADDLE_AUTOSCALE_HYSTERESIS", 2, int))
        if self.hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        # disaggregated per-pool watermarks (role_aware mode): prefill
        # defaults inherit the global queue watermarks; decode kv
        # headroom inherits the global one; session-depth watermarks
        # are decode-pool-only (a mixed cluster has no such signal)
        self.role_aware = bool(
            role_aware if role_aware is not None
            else _env("PADDLE_AUTOSCALE_ROLE_AWARE", 0, int))
        self.pf_queue_high = float(
            pf_queue_high if pf_queue_high is not None
            else _env("PADDLE_AUTOSCALE_PF_QUEUE_HIGH",
                      self.queue_high, float))
        self.pf_queue_low = float(
            pf_queue_low if pf_queue_low is not None
            else _env("PADDLE_AUTOSCALE_PF_QUEUE_LOW",
                      self.queue_low, float))
        if not 0 <= self.pf_queue_low < self.pf_queue_high:
            raise ValueError(
                f"need 0 <= pf_queue_low ({self.pf_queue_low}) < "
                f"pf_queue_high ({self.pf_queue_high})")
        self.dc_kv_free_low = float(
            dc_kv_free_low if dc_kv_free_low is not None
            else _env("PADDLE_AUTOSCALE_DC_KV_FREE_FRAC",
                      self.kv_free_low, float))
        self.dc_sessions_high = float(
            dc_sessions_high if dc_sessions_high is not None
            else _env("PADDLE_AUTOSCALE_DC_SESSIONS_HIGH", 0.85, float))
        self.dc_sessions_low = float(
            dc_sessions_low if dc_sessions_low is not None
            else _env("PADDLE_AUTOSCALE_DC_SESSIONS_LOW", 0.3, float))
        if not 0 <= self.dc_sessions_low < self.dc_sessions_high:
            raise ValueError(
                f"need 0 <= dc_sessions_low ({self.dc_sessions_low}) "
                f"< dc_sessions_high ({self.dc_sessions_high})")
        self.clock = clock or time.monotonic
        self.name_prefix = name_prefix
        # serializes tick / scale_to / the gateway's drain path: the
        # health sweep, POST /admin/scale, and POST /admin/drain all run
        # in executor threads — unserialized, two concurrent scale-downs
        # can each pass the min-replica check and drain the cluster to
        # zero with no recovery path (decide() then reads the empty
        # cluster as "down" forever)
        self._op_lock = threading.RLock()
        self._seq = 0
        self._streak_dir = None           # pending decision direction
        self._streak = 0                  # consecutive agreeing ticks
        self._last_scale_t = None
        # None = not yet seeded: the engines' violated_queue counters
        # are CUMULATIVE window counters, so the first real reading
        # must become the baseline, not a delta — otherwise attaching
        # an autoscaler to a cluster with violation history spawns a
        # replica on a quiet cluster at the first tick
        self._last_violated_queue = None
        self.ticks = 0

    # ---------------------------------------------------------- signals
    def signals(self):
        """One reading of the scaling inputs off the router's snapshot
        cache (refreshing it first)."""
        self.router.refresh()
        with self.router._lock:
            names = self.router.placeable_names()
            snaps = [self.router._snap(n) for n in names]
        snaps = [s for s in snaps if s is not None]
        n = max(len(snaps), 1)
        qmean = sum(int(s.get("queue_depth", 0)) for s in snaps) / n
        kv_free = 1.0
        for s in snaps:
            b = s.get("kv_blocks")
            if b and b.get("kv_blocks_total"):
                kv_free = min(kv_free, b["kv_blocks_free"]
                              / b["kv_blocks_total"])
        # scale on HIGH-priority queue pain only (snapshot v4 splits
        # violated_queue by class): low-class violations under overload
        # are the QoS layer degrading gracefully — spawning a replica
        # for them defeats the priority shed. Snapshots without the
        # per-class split (none today; defensive) fall back to totals.
        vq = 0
        for s in snaps:
            slo = s.get("slo") or {}
            by_cls = slo.get("violated_queue_by_class")
            vq += int(by_cls["high"] if by_cls is not None
                      else slo.get("violated_queue", 0))
        return {"replicas": len(names), "snapshots": len(snaps),
                "queue_mean": qmean, "kv_free_frac": kv_free,
                "slo_violated_queue": vq}

    def signals_roles(self):
        """One per-pool reading for role-aware scaling: the PREFILL
        pool is scored by queue pressure (its work arrives as prompt
        backlog), the DECODE pool by kv headroom and worst resident-
        session depth (its work is sessions pinning slots + blocks).
        Mixed replicas belong to neither pool — they scale on the
        classic global path only."""
        self.router.refresh()
        with self.router._lock:
            pf, dc = [], []
            for n in self.router.placeable_names():
                role = self.router.roles.get(n, "mixed")
                if role == "prefill":
                    pf.append(self.router._snap(n))
                elif role == "decode":
                    dc.append(self.router._snap(n))
        n_pf, n_dc = len(pf), len(dc)
        pf = [s for s in pf if s is not None]
        dc = [s for s in dc if s is not None]
        qmean = (sum(int(s.get("queue_depth", 0)) for s in pf)
                 / max(len(pf), 1))
        kv_free, sess = 1.0, 0.0
        for s in dc:
            b = s.get("kv_blocks")
            if b and b.get("kv_blocks_total"):
                kv_free = min(kv_free, b["kv_blocks_free"]
                              / b["kv_blocks_total"])
            if s.get("num_slots"):
                sess = max(sess, (s["num_slots"] - s["slots_free"])
                           / s["num_slots"])
        return {"prefill_replicas": n_pf, "decode_replicas": n_dc,
                "prefill_snapshots": len(pf),
                "decode_snapshots": len(dc),
                "prefill_queue_mean": qmean,
                "decode_kv_free_frac": kv_free,
                "decode_sessions_frac": sess}

    def decide_roles(self, sig):
        """Pure per-pool watermark logic for ONE ``signals_roles``
        reading: ``("up"|"down", "prefill"|"decode")`` or None. The
        pools scale on DIFFERENT signal families — prefill on queue
        depth, decode on kv headroom + resident sessions. Scale-up
        wins over scale-down when both fire, and prefill backlog
        beats decode pressure (the backlog is user-visible TTFT).
        Bounds/hysteresis/cooldown live in ``tick`` — this stays a
        unit-testable truth table."""
        if sig["prefill_snapshots"] > 0 \
                and sig["prefill_queue_mean"] > self.pf_queue_high:
            return ("up", "prefill")
        if sig["decode_snapshots"] > 0 \
                and (sig["decode_kv_free_frac"] < self.dc_kv_free_low
                     or sig["decode_sessions_frac"]
                     > self.dc_sessions_high):
            return ("up", "decode")
        if sig["prefill_snapshots"] > 0 \
                and sig["prefill_queue_mean"] < self.pf_queue_low:
            return ("down", "prefill")
        if sig["decode_snapshots"] > 0 \
                and sig["decode_sessions_frac"] < self.dc_sessions_low \
                and sig["decode_kv_free_frac"] > self.dc_kv_free_low:
            return ("down", "decode")
        return None

    def decide(self, sig):
        """Pure watermark logic for ONE signal reading: ``"up"``,
        ``"down"``, or None. Hysteresis/cooldown/bounds live in
        ``tick`` — this stays unit-testable as a truth table."""
        vq_delta = (0 if self._last_violated_queue is None
                    else max(sig["slo_violated_queue"]
                             - self._last_violated_queue, 0))
        if (sig["queue_mean"] > self.queue_high
                or sig["kv_free_frac"] < self.kv_free_low
                or vq_delta > 0):
            return "up"
        if sig.get("snapshots", 1) == 0:
            # no snapshot data at all (every placeable replica's fetch
            # failed — e.g. busy rpc workers timing out the liveness
            # probe during a load spike): the zeroed signals would read
            # as an idle cluster and drain healthy, saturated capacity
            # exactly when load is highest. No data -> hold.
            return None
        if sig["queue_mean"] < self.queue_low:
            return "down"
        return None

    # ------------------------------------------------------------- loop
    def tick(self):
        """One control iteration; returns "up"/"down" when a scale
        event fired, else None. Serialized with scale_to()/drain():
        at most one scale operation is in flight at a time."""
        with self._op_lock:
            self.ticks += 1
            # the min-replica FLOOR is an invariant, not a load signal:
            # an operator /admin/drain (guarded only against the LAST
            # replica) or a replica death can leave the set below it,
            # and no watermark would ever fire on an idle cluster —
            # restore it now, bypassing hysteresis and cooldown (a
            # failing spawn hook is retried at the sweep cadence; the
            # gateway's health loop swallows the exception)
            if self.role_aware:
                return self._tick_roles()
            if len(self.router.placeable_names()) < self.min_replicas:
                self._scale_up()
                self._last_scale_t = self.clock()
                self._streak_dir, self._streak = None, 0
                return "up"
            sig = self.signals()
            want = self.decide(sig)
            # goodput violations are EVENT-shaped (a delta consumed by
            # the baseline update below), so the consecutive-tick
            # hysteresis meant for level signals could never be met by
            # them alone — and a violated SLO is already lagging
            # evidence of damage done. New violations bypass the
            # streak requirement (cooldown still rate-limits).
            vq_event = (self._last_violated_queue is not None
                        and sig.get("snapshots", 1) > 0
                        and sig["slo_violated_queue"]
                        > self._last_violated_queue)
            if sig.get("snapshots", 1) > 0:
                # don't let a snapshot outage zero the baseline — the
                # counters' full history would read as a fresh delta
                # (spurious scale-up) when the snapshots return
                self._last_violated_queue = sig["slo_violated_queue"]
            if want != self._streak_dir:
                self._streak_dir, self._streak = want, 0
            if want is None:
                return None
            self._streak += 1
            if self._streak < self.hysteresis \
                    and not (want == "up" and vq_event):
                return None
            now = self.clock()
            if self._last_scale_t is not None \
                    and now - self._last_scale_t < self.cooldown_s:
                return None
            # bound check against the CURRENT placeable count, not the
            # signal reading — an /admin drain may have landed between
            # signals() and here
            n = len(self.router.placeable_names())
            if want == "up" and n < self.max_replicas:
                self._scale_up()
            elif want == "down" and n > self.min_replicas:
                self._scale_down()
            else:
                return None               # at a bound: keep watching
            self._last_scale_t = now
            self._streak_dir, self._streak = None, 0
            return want

    def _tick_roles(self):
        """One role-aware control iteration (caller holds _op_lock):
        pools are repaired first (each must keep >= 1 replica — an
        empty prefill pool strands every new prompt, an empty decode
        pool strands every prefilled session), then at most one
        watermark-driven per-pool scale event fires. Returns
        "up:prefill"-style verdicts."""
        with self.router._lock:
            names = self.router.placeable_names()
            by_pool = {"prefill": [], "decode": []}
            mixed = 0
            for n in names:
                role = self.router.roles.get(n, "mixed")
                if role in by_pool:
                    by_pool[role].append(n)
                else:
                    mixed += 1
        # pool-floor repair bypasses hysteresis/cooldown like the
        # classic min-floor (mixed replicas cover for either pool)
        for pool in ("prefill", "decode"):
            if not by_pool[pool] and not mixed \
                    and len(names) < self.max_replicas:
                self._scale_up(pool)
                self._last_scale_t = self.clock()
                self._streak_dir, self._streak = None, 0
                return f"up:{pool}"
        sig = self.signals_roles()
        want = self.decide_roles(sig)
        if want != self._streak_dir:
            self._streak_dir, self._streak = want, 0
        if want is None:
            return None
        self._streak += 1
        if self._streak < self.hysteresis:
            return None
        now = self.clock()
        if self._last_scale_t is not None \
                and now - self._last_scale_t < self.cooldown_s:
            return None
        direction, pool = want
        n = len(self.router.placeable_names())
        if direction == "up" and n < self.max_replicas:
            self._scale_up(pool)
        elif direction == "down" and n > self.min_replicas \
                and len(by_pool[pool]) > 1:
            self._scale_down(pool)
        else:
            return None                   # at a bound: keep watching
        self._last_scale_t = now
        self._streak_dir, self._streak = None, 0
        return f"{direction}:{pool}"

    def _scale_up(self, role=None):
        self._seq += 1
        if role is None and self.role_aware:
            # operator scale_to / min-floor repair in role-aware mode:
            # generic capacity goes to the decode pool (sessions live
            # there; the prefill pool scales on its own queue signal)
            role = "decode"
        if role is not None:
            name = f"{self.name_prefix}-{role}-{self._seq}"
            rep = self.spawn(name, role)
        else:
            rep = self.spawn(f"{self.name_prefix}-{self._seq}")
        self.router.add_replica(rep)
        return rep.name

    def _scale_down(self, role=None):
        """Drain the LEAST-loaded placeable replica — fewest live
        sessions to migrate. ``role`` restricts the victim to one
        pool (role-aware mode); the decode pool scores by resident-
        session pressure (no queue term)."""
        if role is None and self.role_aware:
            role = "decode"
        with self.router._lock:
            cands = [n for n in self.router.placeable_names()
                     if role is None
                     or self.router.roles.get(n, "mixed") == role]
            if role is not None and len(cands) <= 1:
                return None               # never drain a pool to zero
            score = (self.router.decode_load_score if role == "decode"
                     else self.router.load_score)
            victim = min(cands, key=lambda n: (
                score(self.router._snap(n)), n))
        self.router.remove_replica(victim, migrate=True)
        return victim

    def scale_to(self, n):
        """Operator override (the gateway's POST /admin/scale): walk
        the replica count to ``n`` (clamped to [min, max]) NOW,
        bypassing hysteresis and cooldown. Returns the clamped
        target."""
        n = max(self.min_replicas, min(int(n), self.max_replicas))
        with self._op_lock:
            guard = 0
            while guard < 64:
                cur = len(self.router.placeable_names())
                if cur == n:
                    break
                if cur < n:
                    self._scale_up()
                else:
                    self._scale_down()
                guard += 1
            self._last_scale_t = self.clock()
            self._streak_dir, self._streak = None, 0
        return n
