"""Cluster serving front-end: OpenAI-compatible gateway, prefix-affinity
router, replica failover.

The missing assembly over the serving stack: PR 8's
``telemetry_snapshot()`` is the routing payload, PR 4/6's radix prefix
store is what makes placement matter, PR 3's heartbeat discipline is
the death detector — this package turns N ``ServingEngine`` replicas
(in-process threads or processes behind ``distributed/rpc.py``) into
ONE ``curl``-able endpoint. See gateway.py / router.py / replica.py /
protocol.py docstrings for the layer contracts, and
``python -m paddle_tpu.serving_cluster`` for a self-contained demo
cluster.

The router is pure host code: nothing here dispatches to the device,
so the per-replica zero-retrace contract is untouched by construction.

The replica set is ELASTIC (PR 12): ``Autoscaler`` (autoscale.py)
spawns/drains replicas on telemetry-snapshot signals (queue depth, kv
headroom, SLO queue violations) under the PADDLE_AUTOSCALE_* knobs,
and a drain LIVE-MIGRATES every in-flight session (KV blocks + sampler
state over export_slot/import_slot — zero re-prefill, greedy
token-identical) instead of killing it; ``/admin/scale`` and
``/admin/drain`` expose the same levers to operators.

The trace plane rides on top (PR 11): one ``X-Request-Id`` trace id
per HTTP request threaded gateway -> router -> replica -> engine and
ACROSS failover (same id, incremented attempt), a router decision
audit ring with per-reason counters, gateway HTTP latency histograms,
and ``export_cluster_trace`` — one merged Perfetto trace for the whole
cluster (trace.py).
"""
from .autoscale import Autoscaler
from .gateway import Gateway
from .protocol import ProtocolError
from .replica import LocalReplica, ReplicaError, RpcReplica, serve_engine
from .router import AUDIT_REASONS, HashRing, NoReplicaError, Router
from .trace import export_cluster_trace

__all__ = ["Gateway", "Router", "HashRing", "LocalReplica",
           "RpcReplica", "serve_engine", "ReplicaError",
           "NoReplicaError", "ProtocolError", "AUDIT_REASONS",
           "Autoscaler", "export_cluster_trace"]
