"""Placement-sensitive request router over N replicas.

The router turns replica handles (replica.py) into one submit/harvest
surface with three policies (``PADDLE_ROUTER_POLICY``):

  * ``round_robin`` — arrival order over alive replicas (the A/B
    baseline: placement-blind).
  * ``least_loaded`` — minimize a load score read from each replica's
    ``telemetry_snapshot()``: ``queue_depth + busy_slots +
    num_slots * kv_used_frac`` (queue pressure, slot pressure, pool
    headroom — the three admission bottlenecks the engine exposes).
  * ``prefix_affinity`` (default) — consistent-hash the FIRST
    ``prefill_cap``-aligned prompt block onto a replica ring, so every
    request sharing a template lands where that template's radix chain
    is already hot (prefix_cache.py); prompts shorter than one block
    carry no shareable block and fall back to least-loaded, and a
    SATURATED owner (queue_depth >= ``PADDLE_ROUTER_SPILL_DEPTH``)
    spills to least-loaded — affinity must never become head-of-line
    blocking. Honesty note: affinity only pays at hit-rate > 0; on
    no-template traffic it IS least-loaded with extra hashing.

Replica death is a first-class path, not an exception trail:
``check_health()`` (the gateway's heartbeat loop) marks a replica dead
when its heartbeat age passes ``PADDLE_GATEWAY_HB_DEAD_S`` and its
liveness probe fails, removes it from the hash ring (consistent
hashing: only ITS keys move), and re-submits every one of its
unfinished assignments elsewhere. Re-submission is idempotent by
gateway request id and replays from the prompt; the assignment
remembers how many tokens were already DELIVERED downstream and skips
that many from the replacement stream — greedy decoding makes the
replayed prefix token-identical, so the client's stream is seamless
(sampled mode re-draws its per-request seed on the new engine and is
documented as NOT replay-identical).

Snapshots are trusted only at the pinned ``SNAPSHOT_SCHEMA_VERSION``:
a replica reporting an unknown version is excluded from load scoring
(counted in ``version_mismatches``) instead of being silently misread.

The replica set is ELASTIC: ``add_replica`` joins a new replica to the
ring (minimal key movement — only the new vnodes' keys change home),
``remove_replica`` drains one gracefully — off the ring immediately,
every live assignment MIGRATED (``export_slot``/``import_slot``: the
session's KV blocks + sampler state move and decode resumes mid-stream
with zero re-prefill, greedy token-identical through the delivered-
prefix skip), then the handle retires. Any migration failure (target
death mid-transfer, rpc timeout, fault injection) degrades that
assignment to the classic failover path — replay from the prompt,
never a hang, never a double delivery. The autoscaler (autoscale.py)
drives both off the telemetry-snapshot signals; scale events and
migrations land in the decision audit and ``/metrics``
(``paddle_gateway_scale_events_total{direction=}``,
``paddle_gateway_migrations_total``/``_aborts_total``).

Gray failure — a replica that is SLOW but alive (degraded host, lossy
rpc link) — is defended in three layers, because death detection never
fires for it (the heartbeat keeps beating):

  * **health scoring** — per-replica first-token-latency EWMA observed
    on the router's own harvest path (plus the engine's step-duration
    EWMA from the v6 snapshot ``health`` block as a cold-start signal),
    judged RELATIVE to the cluster median: ``healthy`` / ``suspect``
    (>= ``PADDLE_ROUTER_SUSPECT_RATIO`` x median) / ``degraded``
    (>= ``PADDLE_ROUTER_BREAKER_RATIO`` x median). Exposed via
    ``health_status()`` -> /healthz and /metrics.
  * **circuit breaker** per replica: closed -> open on a degraded
    verdict or ``PADDLE_ROUTER_BREAKER_ERRS`` accumulated transport/
    snapshot errors; open replicas are shed from placement (never
    declared dead) until ``PADDLE_ROUTER_BREAKER_COOLDOWN_S`` passes;
    then half-open admits <= ``PADDLE_ROUTER_BREAKER_PROBES``
    concurrent probe placements whose first-token latency closes the
    breaker (non-outlier) or re-opens it. Recovery needs no operator.
  * **hedged dispatch** — a GREEDY request whose first token is
    overdue (past the cluster's own TTFT
    p``PADDLE_ROUTER_HEDGE_QUANTILE`` x ``PADDLE_ROUTER_HEDGE_MARGIN``)
    is speculatively re-submitted to the next-best replica;
    first-to-first-token wins, the loser is aborted through the normal
    release path and its tokens are never delivered or billed. Greedy
    decoding makes the two legs bit-identical, so the race is pure
    latency; SAMPLED streams never hedge (each engine submit re-draws
    the per-request seed, so two legs would diverge and the client's
    stream would depend on which leg won). Hedges draw from a
    cluster-wide retry-budget token bucket
    (``PADDLE_ROUTER_RETRY_RATE``/``_BURST``) so a brown-out cannot
    amplify into a retry storm — death failovers also drain the
    bucket but proceed on empty (they are the stream's only copy).

Every placement is AUDITED: the router records WHY each request landed
where it did — policy, per-candidate load scores, chosen replica, and
a reason from ``AUDIT_REASONS`` — in a bounded ring
(``PADDLE_ROUTER_AUDIT_RING``, default 2048), with per-reason counters
in the ``/metrics`` exposition
(``paddle_gateway_route_decisions_total{reason=...}``) and the full
entries merged into the cluster Perfetto export (trace.py). Trace
context rides along: ``submit`` mints (or accepts) a ``trace_id`` and
threads it through every replica submit — failover re-submits carry
the SAME trace id with an incremented attempt, so a kill-drill stream
yields one joined trace.
"""
from __future__ import annotations

import bisect
import hashlib
import os
import threading
import time
import uuid
from collections import deque

from ..inference.serving import AdmissionFull
from ..inference.telemetry import LogHistogram, SNAPSHOT_SCHEMA_VERSION
from .replica import ReplicaError

__all__ = ["HashRing", "Router", "NoReplicaError", "POLICIES",
           "AUDIT_REASONS"]

POLICIES = ("prefix_affinity", "least_loaded", "round_robin")

# every reason a placement decision can record (pinned by
# tools/check_metrics_surface.py — the audit counters' label set must
# not drift): affinity_hit = consistent-hash owner took it, spill =
# saturated/shedding owner overflowed to least-loaded, least_loaded /
# round_robin = the policy's own choice, failover = re-submit after a
# replica death, orphaned = failover found nowhere to go, migrated =
# a live session moved to a new replica during a drain, scale_up /
# scale_down = the elastic control plane changed the replica set
# (autoscaler watermark trip or an /admin scale command), hedge = a
# speculative duplicate of an overdue greedy request (gray-failure
# defense; first-to-first-token wins, the loser is aborted)
AUDIT_REASONS = ("affinity_hit", "least_loaded", "round_robin", "spill",
                 "failover", "orphaned", "migrated", "scale_up",
                 "scale_down", "hedge")


class NoReplicaError(ReplicaError):
    """Every replica is dead/unreachable — the gateway maps this to 503
    (service unavailable), distinct from 429 backpressure."""


def _hash64(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring with virtual nodes: add/remove a replica
    moves only the keys it owns (~K/N of them), which is exactly the
    prefix-affinity requirement — a replica death must not reshuffle
    every template's home and cold-start every other radix store."""

    def __init__(self, vnodes=64):
        self.vnodes = int(vnodes)
        self._points = []                 # sorted [(hash, name)]
        self.names = set()

    def add(self, name):
        if name in self.names:
            return
        self.names.add(name)
        for i in range(self.vnodes):
            h = _hash64(f"{name}#{i}".encode())
            bisect.insort(self._points, (h, name))

    def remove(self, name):
        if name not in self.names:
            return
        self.names.discard(name)
        self._points = [(h, n) for h, n in self._points if n != name]

    def owner(self, key: bytes):
        """The replica owning ``key`` (first point clockwise), or None
        on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, (_hash64(key), b""))
        if i == len(self._points):
            i = 0
        return self._points[i][1]


def _locked(fn):
    """Serialize a Router method on the instance lock (see the class
    docstring's thread-safety contract). RLock: harvest -> mark_dead ->
    _place nest on the same thread."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class _Assignment:
    __slots__ = ("gid", "request_id", "prompt", "kw", "replica", "rid",
                 "tokens", "skip", "done", "state", "resubmits",
                 "t_submit", "orphaned", "failed", "dup_returns",
                 "trace_id", "ho_target", "ho_tag", "ho_blocks",
                 "ho_busy", "t_placed", "first_seen", "hedged",
                 "hg_replica", "hg_rid", "hg_t")

    def __init__(self, gid, request_id, prompt, kw, replica, rid,
                 t_submit, trace_id=None):
        self.gid = gid
        self.request_id = request_id
        self.trace_id = trace_id          # cluster trace context
        self.prompt = prompt
        self.kw = kw
        self.replica = replica            # None = placement in flight
        self.rid = rid
        self.tokens = []                  # full de-duplicated history:
        self.skip = 0                     # replayed prefix to drop
        self.done = False                 # every harvested token lands
        self.state = "running"            # here exactly once, so N
        self.resubmits = 0                # concurrent readers can each
        self.t_submit = t_submit          # stream from their own cursor
        self.orphaned = False
        self.failed = None                # placement exception, if any
        self.dup_returns = 0              # idempotent-retry handouts
        # streamed prefill->decode handoff state: the decode replica
        # holding this session's staged KV prefix, the staging tag it
        # filed under, and the block cursor (how many leading blocks
        # are already over there — export_slot skips exactly these)
        self.ho_target = None
        self.ho_tag = None
        self.ho_blocks = 0
        self.ho_busy = False              # one streaming ship at a time
        # gray-failure defense: when the CURRENT leg was placed (the
        # first-token latency anchor — re-set on failover/migration/
        # hedge promotion so TTFT attributes to the serving replica),
        # whether a fresh token has been observed, and the hedge leg
        self.t_placed = t_submit
        self.first_seen = False
        self.hedged = False               # one hedge per request, ever
        self.hg_replica = None            # hedge leg: replica name
        self.hg_rid = None                # hedge leg: engine rid
        self.hg_t = 0.0                   # hedge leg: placement time


class Router:
    """See the module docstring. All waits are the caller's: submit and
    harvest are single bounded calls; health checking is explicit
    (``check_health``), so a virtual-clock bench or a deterministic test
    can drive the whole failure path without sleeping.

    Thread-safety: the gateway drives this from multiple thread-pool
    executor threads (one per in-flight HTTP request) plus the health
    loop. ONE reentrant lock guards all router state (gid allocation,
    the assignment table, the ring, the dead set, snapshots) — but
    replica I/O (submit/harvest/snapshot/probe over a lock or rpc) is
    ALWAYS performed outside it, so a frozen replica stalls only the
    calls touching it, never the whole front-end. Races with failover
    are resolved by re-checking the assignment's (replica, rid) epoch
    after the I/O: a harvest that lost the race discards its batch
    (the replacement replays those tokens), and each harvested token
    lands in the assignment's history exactly once."""

    def __init__(self, replicas, policy=None, spill_depth=None,
                 hb_dead_s=None, snap_max_age_s=None, clock=None,
                 audit_ring=None, handoff_blocks=None,
                 suspect_ratio=None, breaker_ratio=None,
                 breaker_errs=None, breaker_cooldown_s=None,
                 breaker_probes=None, hedge_quantile=None,
                 hedge_margin=None, hedge_min_s=None,
                 retry_rate=None, retry_burst=None):
        self.replicas = {r.name: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica names must be unique")
        # pool roles, read once at registration (engine-construction
        # config, immutable): prefill workers take fresh prompts only,
        # decode workers take handed-off resident sessions only, mixed
        # (the default everywhere) takes both — today's behavior
        self.roles = {n: str(getattr(r, "role", "mixed"))
                      for n, r in self.replicas.items()}
        # streamed-handoff chunk: ship a prefilling session's committed
        # KV to its decode target once this many NEW full blocks exist
        # (0 = ship only at prompt completion, no mid-prefill overlap)
        self._handoff_blocks = int(
            handoff_blocks if handoff_blocks is not None
            else os.environ.get("PADDLE_ROLE_HANDOFF_BLOCKS", "0"))
        self.handoffs_total = 0
        self.policy = policy or os.environ.get("PADDLE_ROUTER_POLICY",
                                               "prefix_affinity")
        if self.policy not in POLICIES:
            raise ValueError(f"unknown router policy {self.policy!r} "
                             f"(choose from {POLICIES})")
        self.spill_depth = int(
            spill_depth if spill_depth is not None
            else os.environ.get("PADDLE_ROUTER_SPILL_DEPTH", "4"))
        self.hb_dead_s = float(
            hb_dead_s if hb_dead_s is not None
            else os.environ.get("PADDLE_GATEWAY_HB_DEAD_S", "2.0"))
        self.snap_max_age_s = float(
            snap_max_age_s if snap_max_age_s is not None
            else os.environ.get("PADDLE_ROUTER_SNAP_AGE_S", "0.25"))
        self.clock = clock or time.monotonic
        self._lock = threading.RLock()
        self.ring = HashRing()
        for name in sorted(self.replicas):
            self.ring.add(name)
        self.dead = set()
        self._snaps = {}                  # name -> (snapshot, t)
        self._rr = 0                      # round-robin cursor
        self._gid = 0
        self._table = {}                  # gid -> _Assignment
        self._by_request_id = {}          # idempotency key -> gid
        self.submits_total = 0
        self.failovers_total = 0
        self.version_mismatches = 0
        self._prefill_cap = None
        # placement decision audit: bounded ring of WHY each request
        # landed where it did, plus per-reason counters (exposed in
        # /metrics and merged into the cluster Perfetto export)
        ar = int(audit_ring if audit_ring is not None
                 else os.environ.get("PADDLE_ROUTER_AUDIT_RING", "2048"))
        if ar < 0:
            raise ValueError(f"audit ring must be >= 0, got {ar}")
        # 0 disables the ring (no per-decision entry is built or
        # stored) but the per-reason counters stay — they're pinned in
        # /metrics by tools/check_metrics_surface.py and cost one dict
        # increment per placement
        self.audit_enabled = ar > 0
        self.audit = deque(maxlen=max(ar, 1))
        self.audit_counts = {r: 0 for r in AUDIT_REASONS}
        # elastic control plane: replicas mid-drain take no NEW
        # placements but keep serving their existing assignments until
        # every one has migrated off; scale/migration counters ride
        # /metrics next to the decision counters
        self.draining = set()
        self.migrations_total = 0
        self.migration_aborts_total = 0
        self.scale_events = {"up": 0, "down": 0}
        # (t, sum of replica finished counters) samples from refresh():
        # the measured queue-drain rate behind retry_after_s(). Samples
        # are spaced at least _drain_gap_s apart — refresh() runs on
        # EVERY submit, so a 429 retry storm would otherwise collapse
        # the 16-slot window to milliseconds in which nothing finished
        # and retry_after_s would report the cap while the queue
        # actually drains fine (each retry re-collapsing the window)
        self._drain_samples = deque(maxlen=16)
        self._drain_gap_s = 0.25
        # ---- gray-failure defense (see module docstring) ----------
        # health scoring: router-observed first-token latency EWMA per
        # replica (it sees queueing AND service on the real placement
        # path), plus the cluster-wide TTFT histogram the hedge delay
        # derives from. Verdicts are cluster-MEDIAN-relative: absolute
        # thresholds would need per-model tuning, and tail-at-scale
        # defense only cares about outliers anyway.
        self.suspect_ratio = float(
            suspect_ratio if suspect_ratio is not None
            else os.environ.get("PADDLE_ROUTER_SUSPECT_RATIO", "3.0"))
        self.breaker_ratio = float(
            breaker_ratio if breaker_ratio is not None
            else os.environ.get("PADDLE_ROUTER_BREAKER_RATIO", "6.0"))
        self.breaker_errs = int(
            breaker_errs if breaker_errs is not None
            else os.environ.get("PADDLE_ROUTER_BREAKER_ERRS", "3"))
        self.breaker_cooldown_s = float(
            breaker_cooldown_s if breaker_cooldown_s is not None
            else os.environ.get("PADDLE_ROUTER_BREAKER_COOLDOWN_S",
                                "2.0"))
        self.breaker_probes = int(
            breaker_probes if breaker_probes is not None
            else os.environ.get("PADDLE_ROUTER_BREAKER_PROBES", "1"))
        # hedged dispatch: 0 disables; the delay derives from the
        # cluster's OWN TTFT distribution, not a configured constant
        self.hedge_quantile = float(
            hedge_quantile if hedge_quantile is not None
            else os.environ.get("PADDLE_ROUTER_HEDGE_QUANTILE", "95"))
        self.hedge_margin = float(
            hedge_margin if hedge_margin is not None
            else os.environ.get("PADDLE_ROUTER_HEDGE_MARGIN", "2.0"))
        self.hedge_min_s = float(
            hedge_min_s if hedge_min_s is not None
            else os.environ.get("PADDLE_ROUTER_HEDGE_MIN_S", "0.02"))
        # cluster-wide retry budget (token bucket over retries+hedges)
        self.retry_rate = float(
            retry_rate if retry_rate is not None
            else os.environ.get("PADDLE_ROUTER_RETRY_RATE", "8.0"))
        self.retry_burst = float(
            retry_burst if retry_burst is not None
            else os.environ.get("PADDLE_ROUTER_RETRY_BURST", "16"))
        self._ttft_ewma = {}              # name -> first-token EWMA (s)
        self._ttft_seen = {}              # name -> observation count
        self.hist_ttft = LogHistogram()   # cluster-wide (hedge delay)
        self._breaker = {}                # name -> breaker record
        self.breaker_transitions = {"open": 0, "half_open": 0,
                                    "closed": 0}
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.retry_budget_exhausted_total = 0
        self._retry_tokens = self.retry_burst
        self._retry_t = self.clock()

    # -------------------------------------------------------- snapshots
    def alive_names(self):
        return [n for n in sorted(self.replicas) if n not in self.dead]

    def placeable_names(self):
        """Alive AND not draining — the placement candidate set. A
        draining replica still serves (and is harvested for) its
        existing assignments until the drain moves them off."""
        return [n for n in self.alive_names() if n not in self.draining]

    def refresh(self, force=False):
        """Pull each alive replica's telemetry snapshot (the routing
        payload), at most once per ``snap_max_age_s`` unless forced. A
        replica that errors here is NOT declared dead — one flaky
        snapshot must not drain a healthy replica; its stale snapshot
        is dropped (it scores worst until it answers again) and the
        death verdict stays with check_health's heartbeat + liveness
        probe (and with actual failed submits/harvests).

        Deliberately NOT @_locked around the replica I/O: when the
        health loop refreshes, a frozen rpc worker must stall only ITS
        snapshot call, never every submit/harvest waiting on the
        router lock. (A submit-path refresh still runs under the
        caller's RLock frame — the short rpc snapshot timeout bounds
        that case.)"""
        now = self.clock()
        with self._lock:
            todo = []
            for name in self.placeable_names():
                got = self._snaps.get(name)
                if force or got is None \
                        or now - got[1] > self.snap_max_age_s:
                    todo.append(name)
        fetched = {}
        for name in todo:
            try:
                fetched[name] = self.replicas[name].snapshot()
            except ReplicaError:
                fetched[name] = None
        with self._lock:
            for name, snap in fetched.items():
                if name in self.dead:
                    continue
                if snap is None:
                    self._snaps.pop(name, None)
                    # breaker input, NOT a death verdict: enough
                    # accumulated snapshot/transport errors shed the
                    # replica from placement (state "open") while the
                    # heartbeat keeps it alive
                    self._breaker_err(name)
                elif snap.get("schema_version") != \
                        SNAPSHOT_SCHEMA_VERSION:
                    # unknown payload: refuse to score it (drop any
                    # stale cached one too) rather than misread it
                    self.version_mismatches += 1
                    self._snaps.pop(name, None)
                else:
                    self._snaps[name] = (snap, now)
                    self._prefill_cap = snap["prefill_cap"]
                    br = self._breaker.get(name)
                    if br is not None and br["errs"]:
                        # DECAY (not reset) on success: a lossy link
                        # alternating ok/error must still accumulate
                        # toward the breaker threshold
                        br["errs"] -= 1
            # drain-rate sample for retry_after_s: the cluster-wide
            # finished count at this instant (engine window counters —
            # monotonic between resets; a negative step from a replica
            # leaving/reset invalidates the window, handled there)
            total_fin = 0
            saw = False
            for name in self.placeable_names():
                got = self._snaps.get(name)
                if got is not None:
                    total_fin += int(got[0].get("requests", {})
                                     .get("finished", 0))
                    saw = True
            if saw and (not self._drain_samples
                        or now - self._drain_samples[-1][0]
                        >= self._drain_gap_s):
                self._drain_samples.append((now, total_fin))

    def _snap(self, name):
        got = self._snaps.get(name)
        return got[0] if got else None

    def retry_after_s(self):
        """429 Retry-After from the MEASURED queue drain rate: total
        queued requests / (finished per second over the recent refresh
        window), floored at protocol.RETRY_AFTER_S and capped at
        protocol.RETRY_AFTER_MAX_S. No backlog or no data yet -> the
        floor; a backlog with zero observed drain -> the cap (honest
        "back off hard" instead of an invented number)."""
        import math

        from . import protocol
        with self._lock:
            qd = 0
            for name in self.placeable_names():
                snap = self._snap(name)
                if snap is not None:
                    qd += int(snap.get("queue_depth", 0))
            samples = list(self._drain_samples)
        lo, hi = protocol.RETRY_AFTER_S, protocol.RETRY_AFTER_MAX_S
        if qd <= 0 or len(samples) < 2:
            return lo
        dt = samples[-1][0] - samples[0][0]
        df = samples[-1][1] - samples[0][1]
        if df < 0:
            # a replica retired/reset mid-window: the cumulative count
            # stepped backwards, the window is garbage — drop it
            with self._lock:
                self._drain_samples.clear()
            return lo
        if dt <= 0 or df == 0:
            return hi
        return int(min(max(math.ceil(qd / (df / dt)), lo), hi))

    def qos_pressure(self):
        """Cluster-wide overload reading for the gateway's SLO-aware
        shed: mean queue depth over placeable replicas plus the
        cumulative queue-vs-service violation split (PR 11's
        decomposition, summed from the snapshot cache — no rpc)."""
        with self._lock:
            names = self.placeable_names()
            snaps = [self._snap(n) for n in names]
        snaps = [s for s in snaps if s is not None]
        qmean = (sum(int(s.get("queue_depth", 0)) for s in snaps)
                 / max(len(snaps), 1))
        vq = sum(int((s.get("slo") or {}).get("violated_queue", 0))
                 for s in snaps)
        vs = sum(int((s.get("slo") or {}).get("violated_service", 0))
                 for s in snaps)
        return {"queue_mean": qmean, "violated_queue": vq,
                "violated_service": vs}

    @staticmethod
    def load_score(snap):
        """queue pressure + slot pressure + pool pressure, one number.
        Missing snapshot scores worst — never prefer a replica you know
        nothing about over one you do."""
        if snap is None:
            return float("inf")
        busy = snap["num_slots"] - snap["slots_free"]
        score = snap["queue_depth"] + busy
        kv = snap.get("kv_blocks")
        if kv and kv["kv_blocks_total"]:
            score += snap["num_slots"] * (kv["kv_blocks_used"]
                                          / kv["kv_blocks_total"])
        return score

    @staticmethod
    def decode_load_score(snap):
        """Decode-pool placement score: resident sessions + pool
        residency, NO queue term — decode workers take handed-off
        sessions straight into slots, so backlogged prompts (a prefill
        signal) must not repel a decode target whose slots and pool
        are actually free."""
        if snap is None:
            return float("inf")
        score = snap["num_slots"] - snap["slots_free"]
        kv = snap.get("kv_blocks")
        if kv and kv["kv_blocks_total"]:
            score += snap["num_slots"] * (kv["kv_blocks_used"]
                                          / kv["kv_blocks_total"])
        return score

    # ------------------------------------------------------------ roles
    def prefill_capable(self, name):
        """Can ``name`` run a prompt from scratch? Fresh submits and
        failover replays (both re-prefill) may only land here."""
        return self.roles.get(name, "mixed") in ("prefill", "mixed")

    def decode_capable(self, name):
        """Can ``name`` decode a resident session? Handoffs and
        mid-decode migrations may only land here — never on a
        prefill-only worker (it would hold the session forever)."""
        return self.roles.get(name, "mixed") in ("decode", "mixed")

    # -------------------------------------------------------- placement
    def _least_loaded(self, names):
        return min(names, key=lambda n: (self.load_score(self._snap(n)),
                                         n))

    def prefix_key(self, prompt):
        """The affinity key: the first ``prefill_cap``-aligned prompt
        block (bytes), or None when the prompt is shorter than one
        block (nothing shareable to be affine about)."""
        cap = self._prefill_cap
        if cap is None or len(prompt) < cap:
            return None
        return ",".join(str(int(t)) for t in prompt[:cap]).encode()

    def _choose(self, prompt, names):
        """One policy choice over ``names``: returns ``(name, reason)``
        with reason from AUDIT_REASONS — the decision audit records WHY
        alongside WHERE."""
        if self.policy == "round_robin":
            self._rr += 1
            return names[self._rr % len(names)], "round_robin"
        if self.policy == "least_loaded":
            return self._least_loaded(names), "least_loaded"
        key = self.prefix_key(prompt)
        if key is None:
            return self._least_loaded(names), "least_loaded"
        owner = self.ring.owner(key)
        if owner not in names:
            return self._least_loaded(names), "least_loaded"
        snap = self._snap(owner)
        if snap is not None and snap["queue_depth"] >= self.spill_depth:
            # saturation spill: the hot replica keeps its cache, the
            # overflow goes wherever there is headroom
            return self._least_loaded(names), "spill"
        return owner, "affinity_hit"

    def _record_decision(self, asg, chosen, reason, scores, attempt):
        """Append one audit entry (bounded ring) + bump its reason
        counter. JSON-able by construction (the cluster trace export
        and tools/slo_report.py both consume entries verbatim):
        unknown-snapshot scores (inf) are recorded as None. Ring size
        0 skips the entry entirely; the reason counter always bumps."""
        entry = None
        if self.audit_enabled:
            entry = {
                "t": self.clock(),
                "gid": asg.gid,
                "trace_id": asg.trace_id,
                "attempt": int(attempt),
                "policy": self.policy,
                "chosen": chosen,
                "reason": reason,
                "scores": {n: (None if s == float("inf")
                               else round(s, 4))
                           for n, s in scores.items()},
            }
        with self._lock:
            if entry is not None:
                self.audit.append(entry)
            self.audit_counts[reason] += 1

    # ---------------------------------------------- gray-failure defense
    def _breaker_of(self, name):
        """Get-or-create one replica's breaker record (call under the
        lock)."""
        br = self._breaker.get(name)
        if br is None:
            br = {"state": "closed", "errs": 0, "opened_t": 0.0,
                  "probe_gids": set()}
            self._breaker[name] = br
        return br

    def _breaker_transition(self, name, to):
        """Move one breaker to state ``to`` (call under the lock);
        bumps the per-target-state transition counter in /metrics."""
        br = self._breaker_of(name)
        if br["state"] == to:
            return
        br["state"] = to
        self.breaker_transitions[to] += 1
        br["probe_gids"].clear()
        if to == "open":
            br["opened_t"] = self.clock()
        elif to == "closed":
            br["errs"] = 0

    def _breaker_err(self, name):
        """One transport/snapshot error against ``name`` (call under
        the lock). NEVER a death verdict: enough accumulated errors
        OPEN the breaker — shed from placement, still heartbeating —
        and a half-open probe-phase error re-opens immediately."""
        br = self._breaker_of(name)
        br["errs"] += 1
        if br["state"] == "closed" and br["errs"] >= self.breaker_errs:
            self._breaker_transition(name, "open")
        elif br["state"] == "half_open":
            self._breaker_transition(name, "open")

    def _breaker_admits(self, name):
        """Placement gate (call under the lock): open sheds; after
        ``breaker_cooldown_s`` the breaker half-opens and admits at
        most ``breaker_probes`` concurrent probe placements."""
        br = self._breaker.get(name)
        if br is None or br["state"] == "closed":
            return True
        if br["state"] == "open":
            if self.clock() - br["opened_t"] < self.breaker_cooldown_s:
                return False
            self._breaker_transition(name, "half_open")
        # prune probe gids whose request no longer lives here
        # (released / failed over / hedged away before the first
        # token): a vanished probe must not wedge the breaker
        # half-open with its only probe slot occupied forever
        live = set()
        for g in br["probe_gids"]:
            a = self._table.get(g)
            if a is not None and not a.done and a.replica == name:
                live.add(g)
        br["probe_gids"] = live
        return len(br["probe_gids"]) < self.breaker_probes

    def breaker_state(self, name):
        """closed | half_open | open (public, for /healthz + drills)."""
        with self._lock:
            br = self._breaker.get(name)
            return "closed" if br is None else br["state"]

    def _health_signals(self):
        """Per-replica slowness signal in seconds (call under the
        lock; lower = better): the router-observed first-token EWMA
        once it has >= 3 observations, else the engine's own
        step-duration EWMA from the v6 snapshot ``health`` block,
        else None (no data — never judged on ignorance)."""
        vals = {}
        for n in self.alive_names():
            v = None
            if self._ttft_seen.get(n, 0) >= 3:
                v = self._ttft_ewma[n]
            else:
                snap = self._snap(n)
                if snap is not None:
                    sv = float((snap.get("health") or {})
                               .get("step_ewma_s", 0.0) or 0.0)
                    if sv > 0.0:
                        v = sv
            vals[n] = v
        return vals

    def health_status(self):
        """Cluster-median-relative gray-failure verdicts, one entry
        per alive replica: ``{"verdict": healthy|suspect|degraded,
        "signal_s", "median_s", "breaker", "consecutive_errors"}``.
        Judged RELATIVE to the cluster median (suspect_ratio /
        breaker_ratio multiples) — exposed via /healthz and
        /metrics."""
        with self._lock:
            vals = self._health_signals()
            known = sorted(v for v in vals.values() if v is not None)
            med = known[len(known) // 2] if known else None
            out = {}
            for n, v in vals.items():
                verdict = "healthy"
                if (v is not None and med is not None and med > 0.0
                        and len(known) >= 2):
                    if v >= self.breaker_ratio * med:
                        verdict = "degraded"
                    elif v >= self.suspect_ratio * med:
                        verdict = "suspect"
                br = self._breaker.get(n)
                out[n] = {
                    "verdict": verdict,
                    "signal_s": v,
                    "median_s": med,
                    "breaker": ("closed" if br is None
                                else br["state"]),
                    "consecutive_errors": (0 if br is None
                                           else br["errs"]),
                }
            return out

    def _observe_ttft(self, name, dt, gid=None, hist=True):
        """One first-token-latency observation against ``name`` (call
        under the lock): feeds the per-replica EWMA, the cluster TTFT
        histogram (the hedge-delay source), and — when ``gid`` is a
        half-open breaker probe — the probe verdict: close on a
        non-outlier TTFT, re-open on an outlier. ``hist=False`` keeps
        a PENALTY reading (a hedge loser's pending age) out of the
        histogram: it must inflate the sick replica's EWMA, but
        letting it poison the cluster-wide delay source would make
        every subsequent hedge slower exactly when hedges are most
        needed — a positive feedback loop."""
        dt = max(float(dt), 0.0)
        prev = self._ttft_ewma.get(name)
        self._ttft_ewma[name] = dt if prev is None else (
            0.7 * prev + 0.3 * dt)
        self._ttft_seen[name] = self._ttft_seen.get(name, 0) + 1
        if hist:
            self.hist_ttft.observe(dt)
        br = self._breaker.get(name)
        if br is not None and br["state"] == "half_open" \
                and gid in br["probe_gids"]:
            br["probe_gids"].discard(gid)
            others = sorted(
                v for n2, v in self._health_signals().items()
                if n2 != name and v is not None)
            med = others[len(others) // 2] if others else None
            if med is not None and med > 0.0 \
                    and dt >= self.breaker_ratio * med:
                self._breaker_transition(name, "open")
            else:
                # recovered: seed the EWMA from the fresh probe
                # reading — slow-era history must not re-trip it
                self._ttft_ewma[name] = dt
                self._breaker_transition(name, "closed")

    def _take_retry_token(self, force=False):
        """Cluster-wide retry budget (token bucket over retries +
        hedges). Hedges are SPECULATIVE and strictly require a token;
        a death failover is the stream's ONLY copy, so it proceeds
        even on an empty bucket (``force=True``) — the exhausted
        counter still records that the cluster is in retry debt."""
        with self._lock:
            now = self.clock()
            self._retry_tokens = min(
                self.retry_burst,
                self._retry_tokens + self.retry_rate
                * max(0.0, now - self._retry_t))
            self._retry_t = now
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                return True
            self.retry_budget_exhausted_total += 1
            return bool(force)

    def _drop_hedge(self, asg, dead=None):
        """Release ``asg``'s hedge leg, if any (replica I/O outside
        the lock): the assignment is moving (migration/handoff) or
        ending, and a speculative duplicate must never outlive the
        decision. ``dead`` skips the release on a corpse."""
        with self._lock:
            hg_name, hg_rid = asg.hg_replica, asg.hg_rid
            asg.hg_replica, asg.hg_rid = None, None
            rep = (self.replicas.get(hg_name)
                   if hg_name is not None and hg_name != dead
                   else None)
        if rep is not None:
            rep.release(hg_rid)

    def _maybe_hedge(self, asg):
        """Hedged dispatch trigger (called from the harvest path,
        replica I/O outside the lock): a GREEDY request whose first
        token is overdue — older than the cluster's own TTFT
        p(hedge_quantile) x hedge_margin — is speculatively
        re-submitted to the next-best replica. One hedge per request,
        ever; sampled traffic never hedges (the legs would diverge);
        the retry budget strictly gates it."""
        if self.hedge_quantile <= 0:
            return
        with self._lock:
            if (asg.done or asg.orphaned or asg.hedged
                    or asg.first_seen or asg.replica is None
                    or asg.hg_rid is not None):
                return
            owner = asg.replica
            # greedy-only safety gate (v6 snapshots carry do_sample;
            # absent/unknown reads as NOT greedy — never hedge on a
            # guess): a sampled stream re-draws its per-request seed
            # on each engine submit, so two legs would DIVERGE and
            # the delivered stream would depend on which leg won.
            # Greedy decoding is bit-identical across replicas,
            # making first-to-first-token a pure latency race.
            snap = self._snap(owner)
            if snap is None:
                for n2 in self.placeable_names():
                    snap = self._snap(n2)
                    if snap is not None:
                        break
            if snap is None or snap.get("do_sample") is not False:
                return
            if self.hist_ttft.count < 8:
                return            # no distribution to derive from yet
            p = self.hist_ttft.percentile(self.hedge_quantile)
            delay = max((p or 0.0) * self.hedge_margin,
                        self.hedge_min_s)
            if self.clock() - asg.t_placed <= delay:
                return
            cands = [n for n in self.placeable_names()
                     if n != owner and self.prefill_capable(n)
                     and self._breaker_admits(n)]
            if not cands:
                return
            target = self._least_loaded(cands)
            asg.hedged = True     # one attempt per request, win or lose
            attempt = asg.resubmits + 2
            kw = dict(asg.kw)
        if kw.get("deadline_s") is not None:
            remaining = kw["deadline_s"] - (self.clock()
                                            - asg.t_submit)
            if remaining <= 0:
                return            # the deadline path expires it
            kw["deadline_s"] = remaining
        if not self._take_retry_token():
            return                # budget empty: no speculative copies
        try:
            rid = self.replicas[target].submit(
                asg.prompt, trace_id=asg.trace_id, attempt=attempt,
                **kw)
        except (AdmissionFull, ReplicaError):
            return                # opportunistic: no retry walk
        with self._lock:
            live = (asg.gid in self._table and not asg.done
                    and not asg.orphaned and not asg.first_seen
                    and asg.hg_rid is None)
            if live:
                asg.hg_replica, asg.hg_rid = target, rid
                asg.hg_t = self.clock()
                self.hedges_total += 1
                stray = None
            else:                 # finished/released while submitting
                stray = self.replicas.get(target)
        if stray is not None:
            stray.release(rid)
            return
        self._record_decision(asg, target, "hedge", {}, attempt)

    def _poll_hedge(self, asg, leg, base):
        """Poll ``asg``'s hedge leg (replica I/O outside the lock) and
        decide the race when it produced tokens: promote the leg if
        the owner is still silent (the owner becomes the loser), else
        abort it. The loser is released through the normal path — its
        tokens never enter the delivered history, so they are never
        streamed or billed. Returns the updated harvest triple after
        a promotion, else None."""
        hname, hrid = leg
        rep = self.replicas.get(hname)
        if rep is None:
            with self._lock:
                if (asg.hg_replica, asg.hg_rid) == leg:
                    asg.hg_replica, asg.hg_rid = None, None
            return None
        try:
            hnew, hdone, hstate = rep.harvest(hrid)
        except ReplicaError:
            # the hedge leg was speculative: drop it, leave the death
            # verdict to the heartbeat sweep
            with self._lock:
                if (asg.hg_replica, asg.hg_rid) == leg:
                    asg.hg_replica, asg.hg_rid = None, None
            return None
        loser = None
        out = None
        with self._lock:
            if (asg.hg_replica, asg.hg_rid) != leg \
                    or asg.done or asg.orphaned:
                return None
            if not hnew:
                if hdone:         # zero-token finish: useless leg
                    asg.hg_replica, asg.hg_rid = None, None
                return None
            if asg.first_seen:
                # the owner answered while we polled: hedge lost
                asg.hg_replica, asg.hg_rid = None, None
                loser = leg
            else:
                # hedge wins: promote the leg, the old owner is the
                # loser. Its pending age is ITS first-token
                # observation — the slow replica's EWMA inflates NOW,
                # not whenever it finally answers.
                loser = (asg.replica, asg.rid)
                # gid passes through: if this request was the loser's
                # half-open breaker PROBE, being hedged away IS the
                # probe verdict (an outlier pending age re-opens) —
                # otherwise the probe slot would stay occupied by a
                # request that no longer lives there
                self._observe_ttft(loser[0],
                                   self.clock() - asg.t_placed,
                                   gid=asg.gid, hist=False)
                asg.replica, asg.rid = hname, hrid
                asg.t_placed = asg.hg_t
                asg.hg_replica, asg.hg_rid = None, None
                asg.resubmits += 1
                asg.tokens.extend(hnew)
                asg.first_seen = True
                self._observe_ttft(hname, self.clock() - asg.hg_t,
                                   gid=asg.gid)
                if hdone:
                    asg.done, asg.state = True, hstate
                self.hedge_wins_total += 1
                out = (list(asg.tokens[base:]), asg.done, asg.state)
        if loser is not None:
            lrep = self.replicas.get(loser[0])
            if lrep is not None:
                lrep.release(loser[1])
        return out

    # ------------------------------------------------------- submit path
    def submit(self, prompt, request_id=None, trace_id=None, **kw):
        """Route one request; returns the gateway-global id (gid).
        Idempotent on ``request_id``: a repeat — concurrent or later,
        while the original assignment is live — returns the existing
        gid without re-running anything (the gid is RESERVED before
        the placement I/O, so two simultaneous retries cannot race
        into two engine submissions). AdmissionFull propagates only
        when EVERY alive replica sheds (honest cluster-wide
        backpressure); a replica that dies mid-submit is failed over
        transparently.

        ``trace_id`` is the cluster trace context (the gateway mints
        one per HTTP request, honoring an inbound ``X-Request-Id``);
        direct callers that pass none get a minted id, so every
        placement is traceable. The id survives failover re-submits
        (attempt increments), joining the request's spans across
        replicas."""
        prompt = [int(t) for t in prompt]
        if trace_id is None:
            trace_id = uuid.uuid4().hex
        with self._lock:
            if request_id is not None \
                    and request_id in self._by_request_id:
                gid = self._by_request_id[request_id]
                got = self._table.get(gid)
                if got is not None:
                    got.dup_returns += 1
                return gid
            self._gid += 1
            gid = f"req-{self._gid}"
            asg = _Assignment(gid, request_id, prompt, kw, None, None,
                              self.clock(), trace_id=str(trace_id))
            self._table[gid] = asg
            if request_id is not None:
                self._by_request_id[request_id] = gid
            self.submits_total += 1
        self.refresh()
        try:
            name, rid = self._place(prompt, kw, asg=asg, attempt=1)
        except Exception as e:
            with self._lock:
                # unwind the reservation — unless a concurrent
                # idempotent retry already took this gid home, in
                # which case the entry stays and carries the failure
                # (its harvest re-raises e, so 429 stays 429 instead
                # of decaying into a 404 for the duplicate; the
                # duplicate's release drops the entry)
                if request_id is not None:
                    self._by_request_id.pop(request_id, None)
                if asg.dup_returns:
                    asg.failed = e
                else:
                    self._table.pop(gid, None)
            raise
        with self._lock:
            asg.replica, asg.rid = name, rid
            asg.t_placed = self.clock()
            # the chosen replica may have been declared dead between
            # our successful engine submit and this bookkeeping write
            # — mark_dead's drain skipped the still-placement-pending
            # assignment, so the failover is OURS to run
            raced_death = name in self.dead and not asg.done
            if raced_death:
                asg.replica, asg.rid = None, None
        if raced_death:
            self._failover_one(asg)
        return gid

    def _place(self, prompt, kw, exclude=(), asg=None, attempt=1,
               reason_override=None):
        """One placement attempt over the alive set: policy choice
        first, then the remaining candidates by load on AdmissionFull
        (spill), marking dead anything that errors. The replica submit
        itself runs OUTSIDE the router lock (a frozen replica must not
        stall unrelated requests). Raises the LAST AdmissionFull when
        everyone sheds. A successful placement is recorded in the
        decision audit (reason from the policy choice; ``spill`` once a
        shed forced a retry elsewhere; ``reason_override`` stamps the
        failover path)."""
        last_full = None
        tried = set(exclude)
        shed = False
        while True:
            with self._lock:
                # fresh submits and failover replays both run the
                # prompt from scratch — decode-only workers are never
                # candidates (satellite bugfix: a prefill drain must
                # re-route in-flight prompts to prefill-capable
                # replicas, not strand them on a decode pool)
                names = [n for n in self.placeable_names()
                         if n not in tried and self.prefill_capable(n)]
                # gray-failure shed: an open breaker drops the replica
                # from placement WITHOUT declaring it dead. Availability
                # beats purity — when every candidate's breaker is open
                # the unfiltered set stays (serve slow over serve
                # nothing)
                ok = [n for n in names if self._breaker_admits(n)]
                if ok:
                    names = ok
                if names:
                    name, reason = self._choose(prompt, names)
                    # the per-candidate score dict exists only for the
                    # audit entry — skip it when the ring is off
                    scores = ({n: self.load_score(self._snap(n))
                               for n in names}
                              if self.audit_enabled else {})
                else:
                    name = None
            if name is None:
                if last_full is not None:
                    raise last_full
                raise NoReplicaError(
                    "no alive prefill-capable replica to place on")
            tried.add(name)
            try:
                rid = self.replicas[name].submit(
                    prompt,
                    trace_id=None if asg is None else asg.trace_id,
                    attempt=attempt, **kw)
            except AdmissionFull as e:
                last_full = e
                shed = True               # the next landing is a spill
            except ReplicaError:
                self.mark_dead(name)
            else:
                if asg is not None:
                    with self._lock:
                        br = self._breaker.get(name)
                        if br is not None and br["state"] == "half_open":
                            # this placement IS the recovery probe: its
                            # first-token latency closes or re-opens the
                            # breaker (_observe_ttft)
                            br["probe_gids"].add(asg.gid)
                    self._record_decision(
                        asg, name,
                        reason_override or ("spill" if shed else reason),
                        scores, attempt)
                return name, rid

    # ------------------------------------------------------ harvest path
    def harvest(self, gid, cursor=None):
        """Incremental harvest for one gateway request: ``(new_tokens,
        done, state)``. Every harvested token lands in the
        assignment's history exactly once; ``cursor=None`` returns the
        tokens appended since the last cursorless call (single-reader
        delta semantics), an explicit integer cursor returns
        ``history[cursor:]`` so concurrent readers of one gid (an
        idempotent client retry) each see the complete stream. A
        replica death here triggers the failover re-submit and returns
        an empty batch (the stream stalls one poll interval, never
        errors); the replayed prefix is skipped so the history gets
        each token once. KeyError for an unknown/released gid."""
        with self._lock:
            asg = self._table[gid]
            base = len(asg.tokens) if cursor is None else int(cursor)
            if asg.failed is not None:
                raise asg.failed          # duplicate of a shed submit:
            if asg.done:                  # 429 stays 429, never a 404
                return list(asg.tokens[base:]), True, asg.state
            if asg.orphaned:
                raise NoReplicaError(
                    f"{gid}: no alive replica to fail over to")
            epoch = (asg.replica, asg.rid)
            rep = (None if asg.replica is None
                   else self.replicas[asg.replica])
            if rep is None:               # failover placement in flight
                return list(asg.tokens[base:]), False, "running"
        try:
            new, done, state = rep.harvest(epoch[1])
        except ReplicaError:
            self.mark_dead(epoch[0])
            with self._lock:
                # mark_dead no-ops when the replica was ALREADY dead
                # (e.g. it died between a submit placing here and the
                # bookkeeping write) — if the assignment still points
                # at the corpse, the failover is ours to run
                stuck = (not asg.done and not asg.orphaned
                         and (asg.replica, asg.rid) == epoch)
                if stuck:
                    asg.replica, asg.rid = None, None
            if stuck:
                self._failover_one(asg)
            with self._lock:
                return list(asg.tokens[base:]), False, "running"
        with self._lock:
            if (asg.replica, asg.rid) != epoch:
                # failover raced this harvest: DISCARD the batch — the
                # replacement replays it (skip was set against the
                # history length, which this batch never joined)
                return list(asg.tokens[base:]), False, "running"
            if asg.skip:
                drop = min(asg.skip, len(new))
                asg.skip -= drop
                new = new[drop:]
            asg.tokens.extend(new)
            if new and not asg.first_seen:
                # first delivered token: the owner answered — feed the
                # health EWMA + cluster TTFT histogram (and settle a
                # half-open breaker probe, if this placement was one)
                asg.first_seen = True
                self._observe_ttft(epoch[0],
                                   self.clock() - asg.t_placed,
                                   gid=gid)
            # a hedge leg racing this stream loses the moment the owner
            # produces (or finishes): capture it for release outside
            # the lock. A still-silent owner leaves the leg up for the
            # poll below.
            hg_release = None
            if asg.hg_rid is not None and (new or done):
                hg_release = (asg.hg_replica, asg.hg_rid)
                asg.hg_replica, asg.hg_rid = None, None
            hedge_poll = ((asg.hg_replica, asg.hg_rid)
                          if asg.hg_rid is not None else None)
            if done:
                asg.done, asg.state = True, state
            out = (list(asg.tokens[base:]), done, state)
            # disaggregation hook: this poll is the handoff driver. A
            # session a prefill worker HOLDS (state "prefilled":
            # prompt complete, first token sampled, decode parked)
            # moves to a decode worker now; a still-prefilling session
            # streams its committed KV blocks ahead when the chunk
            # knob is on — the import overlaps the prefill tail.
            src_role = self.roles.get(epoch[0], "mixed")
            handoff = (None if done or src_role != "prefill"
                       else "full" if state == "prefilled"
                       else "stream" if (state == "running"
                                         and self._handoff_blocks > 0)
                       else None)
        if hg_release is not None:
            lrep = self.replicas.get(hg_release[0])
            if lrep is not None:
                lrep.release(hg_release[1])
        if hedge_poll is not None:
            # owner still silent, hedge leg up: poll it — a promotion
            # repoints the assignment at the hedge replica and the
            # delivered stream continues from ITS tokens
            promoted = self._poll_hedge(asg, hedge_poll, base)
            if promoted is not None:
                out = promoted
                done = out[1]
                handoff = None
        elif not done and not out[0]:
            # no tokens, no hedge yet: maybe the owner is gray-slow —
            # the hedge trigger compares its silence to the cluster's
            # own TTFT distribution
            self._maybe_hedge(asg)
        if done:
            self._drop_stage(asg)
        elif handoff == "full":
            self._handoff_one(asg)
        elif handoff == "stream":
            self._handoff_stream(asg)
        return out

    @_locked
    def poll(self, gid):
        asg = self._table.get(gid)
        if asg is None:
            return None
        return {"gid": gid, "replica": asg.replica, "done": asg.done,
                "state": asg.state, "delivered": len(asg.tokens),
                "resubmits": asg.resubmits, "trace_id": asg.trace_id,
                "attempt": asg.resubmits + 1}

    def trace_id_of(self, gid):
        """The trace id riding assignment ``gid`` (None once
        released). The gateway re-reads this after submit: an
        idempotent repeat returns the ORIGINAL submission's gid, and
        the response must echo the trace id the engine spans and the
        decision audit actually carry — not whatever fresh id the
        retry arrived with."""
        with self._lock:
            got = self._table.get(gid)
            return None if got is None else got.trace_id

    def release(self, gid):
        """Forget a finished/abandoned request (client disconnect).
        NOTE: with concurrent readers of one gid (idempotent retry),
        the first release drops the assignment for all of them — the
        gateway maps the survivors' KeyError to 404."""
        with self._lock:
            asg = self._table.pop(gid, None)
            if asg is None:
                return
            if asg.request_id is not None:
                self._by_request_id.pop(asg.request_id, None)
            rep = None
            if not asg.done and not asg.orphaned \
                    and asg.replica is not None:
                rep = self.replicas.get(asg.replica)
        if rep is not None:
            rep.release(asg.rid)
        if asg.hg_rid is not None:
            self._drop_hedge(asg)
        if asg.ho_tag is not None:
            self._drop_stage(asg)

    # ----------------------------------------------------------- health
    def check_health(self):
        """Heartbeat sweep: a replica whose heartbeat age passed
        ``hb_dead_s`` gets ONE bounded liveness probe (outside the
        router lock); failure = dead = drain + re-route. Returns the
        names newly marked dead."""
        with self._lock:
            # a mid-drain replica is the drain's responsibility — its
            # heartbeat may stall while blocks stream off it, and
            # declaring it dead would turn a graceful migrate-then-
            # retire into kill-and-reprefill
            suspects = [n for n in self.placeable_names()
                        if self.replicas[n].heartbeat_age()
                        > self.hb_dead_s]
        died = []
        for name in suspects:
            if self.replicas[name].alive:  # probe refreshes the beat
                continue
            self.mark_dead(name)
            died.append(name)
        # gray-failure sweep: a replica whose latency signal is a
        # breaker_ratio outlier against the cluster median is DEGRADED
        # — open its breaker (shed from placement, keep heartbeating;
        # half-open probes re-admit it once it recovers). Deliberately
        # NOT a death: its in-flight streams keep draining.
        status = self.health_status()
        with self._lock:
            for n, st in status.items():
                if st["verdict"] == "degraded" \
                        and self._breaker_of(n)["state"] == "closed":
                    self._breaker_transition(n, "open")
        return died

    def mark_dead(self, name):
        """Death IS drain: remove from the ring (only its keys move),
        then re-submit every unfinished assignment it held — idempotent
        per assignment (each is re-placed exactly once per death), with
        the delivered-history length remembered so the replayed greedy
        prefix is skipped, not double-streamed. Re-placement I/O runs
        outside the lock; until it lands the assignment's replica is
        None and harvests return empty batches. A deadline_s request
        fails over with its REMAINING budget (measured from the
        original submit) — an already-expired one goes straight to the
        expired state instead of restarting its clock."""
        with self._lock:
            if name in self.dead:
                return
            self.dead.add(name)
            self.ring.remove(name)
            self._snaps.pop(name, None)
            victims = [asg for asg in self._table.values()
                       if asg.replica == name and not asg.done
                       and not asg.orphaned]
            for asg in victims:
                asg.replica, asg.rid = None, None
            # hedge legs parked on the corpse are gone with it
            for asg in self._table.values():
                if asg.hg_replica == name:
                    asg.hg_replica, asg.hg_rid = None, None
        for asg in victims:
            self._failover_one(asg)

    def _failover_one(self, asg):
        """Re-place ONE assignment whose replica is gone (the caller
        already nulled its replica/rid under the lock). Deadline
        requests fail over with their REMAINING budget; a released-
        while-draining assignment (client disconnect racing the drain)
        gets its stray replacement submission released instead of
        leaking a tracked engine record forever."""
        if asg.ho_tag is not None:
            # the replayed prompt re-prefills from scratch — a staged
            # prefix from the dead leg is garbage on its target
            self._drop_stage(asg)
        # a live hedge leg IS the failover, already paid for: promote
        # it instead of burning a third prefill of the same prompt
        with self._lock:
            hg_name, hg_rid = asg.hg_replica, asg.hg_rid
            if hg_rid is not None and hg_name not in self.dead \
                    and hg_name in self.replicas \
                    and asg.gid in self._table \
                    and not asg.done and not asg.orphaned:
                asg.skip = len(asg.tokens)
                asg.replica, asg.rid = hg_name, hg_rid
                asg.t_placed = asg.hg_t
                asg.hg_replica, asg.hg_rid = None, None
                asg.resubmits += 1
                self.failovers_total += 1
                return
            # a leg on a dead/gone replica is just forgotten
            asg.hg_replica, asg.hg_rid = None, None
        kw = dict(asg.kw)
        if kw.get("deadline_s") is not None:
            remaining = kw["deadline_s"] - (self.clock()
                                            - asg.t_submit)
            if remaining <= 0:
                with self._lock:
                    asg.done, asg.state = True, "expired"
                return
            kw["deadline_s"] = remaining
        # death failovers draw on the retry budget but are never
        # blocked by it (force=True): this is the stream's only copy
        self._take_retry_token(force=True)
        # same trace id, NEXT attempt: the re-submitted stream joins
        # the original's trace (resubmits bumps only after placement
        # lands, so attempt = prior resubmits + this one + 1)
        attempt = asg.resubmits + 2
        try:
            new_name, rid = self._place(asg.prompt, kw, asg=asg,
                                        attempt=attempt,
                                        reason_override="failover")
        except (AdmissionFull, NoReplicaError):
            # nowhere to go RIGHT NOW: orphan it honestly; the
            # gateway surfaces 503/429 instead of hanging
            with self._lock:
                asg.orphaned = True
                asg.state = "orphaned"
            self._record_decision(asg, None, "orphaned", {}, attempt)
            return
        with self._lock:
            if asg.gid in self._table and not asg.done:
                asg.skip = len(asg.tokens)
                asg.replica, asg.rid = new_name, rid
                asg.t_placed = self.clock()
                asg.resubmits += 1
                self.failovers_total += 1
                stray = None
            else:                         # released/finished meanwhile
                stray = self.replicas.get(new_name)
        if stray is not None:
            stray.release(rid)

    # -------------------------------------------- disaggregated handoff
    def _drop_stage(self, asg):
        """Abort any streamed-KV prefix parked on a decode target for
        ``asg`` (the session finished, was released, or failed over
        before the handoff consumed it). Staged blocks hold pool
        reservation on the target and would leak forever otherwise.
        Best-effort: a dead target already freed them with its pool."""
        with self._lock:
            tgt, tag = asg.ho_target, asg.ho_tag
            asg.ho_target = asg.ho_tag = None
            asg.ho_blocks = 0
            rep = self.replicas.get(tgt) if tag is not None else None
        if rep is not None:
            try:
                rep.abort_stage(tag)
            except Exception:
                pass                      # corpse cleanup is moot

    @staticmethod
    def _import_headroom_ok(snap, plen, max_new, staged_blocks=0):
        """Would ``import_slot`` on the replica behind ``snap`` admit a
        session of this shape right now? Mirrors the engine's own shed
        gates: a free slot, plus worst-case pool blocks against the
        RESERVATION ledger (``kv_blocks_unreserved``), not residency —
        every free block can already be spoken for by running
        sessions' growth budgets. ``staged_blocks`` already hold their
        own reservation on the target, which transfers into the
        imported session's, so they count toward the need. No
        snapshot (or an old one missing the gauge) reads optimistic:
        the import's own AdmissionFull shed stays the safety net."""
        if snap is None:
            return True
        if snap.get("slots_free", 1) < 1:
            return False
        unres = (snap.get("kv_blocks") or {}).get("kv_blocks_unreserved")
        if unres is None:
            return True
        bt = int(snap.get("prefill_cap", 1)) or 1
        need = -(-(int(plen) + int(max_new)) // bt)
        return unres + staged_blocks >= need

    def _handoff_one(self, asg):
        """Ship one HELD session (engine state "prefilled": prompt
        complete, first token sampled, decode parked) from its prefill
        worker to a decode worker — the disaggregation transfer. With
        a streamed prefix already staged on a decode target
        (``ho_tag`` set), the export skips those blocks and the import
        splices them in: the remaining transfer is just the partial
        tail block plus bookkeeping, so TTFT tracks prefill time
        rather than prefill + full KV copy. No decode capacity RIGHT
        NOW is not an error — the session stays parked on the prefill
        worker (bounced back if the export already happened) and the
        next harvest poll retries: "held" is backpressure, not
        failure. Returns "handed_off" | "held" | "skipped" |
        "failed_over" | "orphaned" | "expired"."""
        # snapshot freshness IS the export/bounce economy here: a slot
        # another handoff filled microseconds ago must read as taken,
        # or this session pays a full KV export + re-import bounce (or
        # worse, a prompt replay when a staged prefix pins the target).
        # refresh() throttles itself to snap_max_age_s, so the steady
        # state costs nothing extra
        self.refresh()
        if asg.hg_rid is not None:
            self._drop_hedge(asg)
        with self._lock:
            if asg.done or asg.orphaned or asg.replica is None \
                    or asg.rid is None or asg.ho_busy:
                return "skipped"
            src_name, rid = asg.replica, asg.rid
            asg.ho_busy = True
            names = self.placeable_names()
            tgt0, tag, cursor = asg.ho_target, asg.ho_tag, asg.ho_blocks
            dead_stage = None
            if tag is not None and (tgt0 not in names
                                    or not self.decode_capable(tgt0)):
                # the staged prefix's target is gone: forget the stage
                # and export the full payload from block 0 instead
                dead_stage = (tgt0, tag)
                asg.ho_target = asg.ho_tag = None
                asg.ho_blocks = 0
                tgt0, tag, cursor = None, None, 0
            max_new = int(asg.kw.get("max_new_tokens", 20))
            plen = len(asg.prompt)
            if tag is not None:
                # a partially-staged session can ONLY land where its
                # prefix lives (import validates staged == kv_skip);
                # it must be admittable BEFORE the export: once
                # export_slot runs, the source slot is gone and a shed
                # import can only fall back to a prompt replay
                targets = [tgt0] if self._import_headroom_ok(
                    self._snap(tgt0), plen, max_new,
                    staged_blocks=cursor) else []
            else:
                # unstaged sessions can go anywhere decode-capable,
                # but exporting toward a full target just buys a
                # bounce (export + re-import on the source, twice the
                # KV traffic for nothing) — screen on the same
                # headroom the import gates on
                targets = sorted(
                    (n for n in names
                     if n != src_name and self.decode_capable(n)
                     and self._import_headroom_ok(
                         self._snap(n), plen, max_new)),
                    key=lambda n: (self.decode_load_score(
                        self._snap(n)), n))
        try:
            if dead_stage is not None and \
                    dead_stage[0] in self.replicas:
                try:
                    self.replicas[dead_stage[0]].abort_stage(
                        dead_stage[1])
                except Exception:
                    pass
            if not targets:
                return "held"
            with self._lock:
                if asg.done or asg.orphaned \
                        or (asg.replica, asg.rid) != (src_name, rid):
                    return "skipped"
                # detach: a concurrent harvest discards its batch on
                # the epoch mismatch, exactly like migration/failover
                asg.replica, asg.rid = None, None
            attempt = asg.resubmits + 2
            src = self.replicas[src_name]
            tgt_name = rid2 = None
            try:
                state = src.export_slot(rid, skip_blocks=cursor)
                if asg.kw.get("deadline_s") is not None:
                    remaining = asg.kw["deadline_s"] - (self.clock()
                                                        - asg.t_submit)
                    if remaining <= 0:
                        with self._lock:
                            asg.done, asg.state = True, "expired"
                        return "expired"
                    state["deadline_s"] = remaining
                state["attempt"] = attempt
            except Exception:
                with self._lock:
                    self.migration_aborts_total += 1
                    stuck = not asg.done and not asg.orphaned
                if stuck:
                    self._failover_one(asg)
                with self._lock:
                    return ("orphaned" if asg.orphaned else
                            "expired" if asg.state == "expired" else
                            "failed_over")
            for cand in targets:
                try:
                    rid2 = self.replicas[cand].import_slot(
                        state, staged=(tag if cand == tgt0 else None))
                except (AdmissionFull, ReplicaError, KeyError):
                    continue
                tgt_name = cand
                break
            if tgt_name is None and cursor == 0:
                # nowhere to decode RIGHT NOW: bounce the full payload
                # back onto the prefill worker — the engine re-holds
                # it ("prefilled") and the next poll retries
                try:
                    rid2 = src.import_slot(state)
                    tgt_name = src_name
                except Exception:
                    pass
            if tgt_name is None:
                # the payload is off every engine (and a skipped
                # prefix, if any, lives only on a target that just
                # refused it) — honest fallback: drop the stage and
                # replay from the prompt
                if tag is not None and tgt0 in self.replicas:
                    try:
                        self.replicas[tgt0].abort_stage(tag)
                    except Exception:
                        pass
                with self._lock:
                    asg.ho_target = asg.ho_tag = None
                    asg.ho_blocks = 0
                    self.migration_aborts_total += 1
                    stuck = not asg.done and not asg.orphaned
                if stuck:
                    self._failover_one(asg)
                with self._lock:
                    return ("orphaned" if asg.orphaned else
                            "expired" if asg.state == "expired" else
                            "failed_over")
            with self._lock:
                if asg.gid in self._table and not asg.done:
                    asg.skip = len(asg.tokens)
                    asg.replica, asg.rid = tgt_name, rid2
                    asg.t_placed = self.clock()
                    stray = None
                    if tgt_name != src_name:
                        asg.resubmits += 1
                        self.handoffs_total += 1
                        asg.ho_target = asg.ho_tag = None
                        asg.ho_blocks = 0
                else:                     # released/finished meanwhile
                    stray = self.replicas.get(tgt_name)
            if stray is not None:
                stray.release(rid2)
                return "skipped"
            if tgt_name == src_name:
                return "held"
            self._record_decision(asg, tgt_name, "migrated", {},
                                  attempt)
            return "handed_off"
        finally:
            with self._lock:
                asg.ho_busy = False

    def _handoff_stream(self, asg):
        """Stream the COMMITTED full KV blocks of a still-prefilling
        session on a prefill worker ahead to a decode target
        (``stage_kv_blocks``): the transfer overlaps the prefill tail,
        so by the time the prompt completes and ``_handoff_one`` runs,
        only the partial tail block is left to move. The cursor
        (``asg.ho_blocks``) advances only after a successful stage —
        a shed (AdmissionFull) just re-reads the same span on the
        next poll (reads are idempotent). No decode target, or fewer
        than ``handoff_blocks`` new committed blocks, is a silent
        no-op."""
        with self._lock:
            if asg.done or asg.orphaned or asg.replica is None \
                    or asg.rid is None or asg.ho_busy:
                return
            src_name, rid = asg.replica, asg.rid
            asg.ho_busy = True
            names = self.placeable_names()
            tgt0, tag, cursor = asg.ho_target, asg.ho_tag, asg.ho_blocks
            if tag is not None and (tgt0 not in names
                                    or not self.decode_capable(tgt0)):
                # stage target died/drained (its pool freed the
                # blocks with it): restart streaming from scratch
                asg.ho_target = asg.ho_tag = None
                asg.ho_blocks = 0
                tgt0, tag, cursor = None, None, 0
            if tag is None:
                cands = sorted(
                    (n for n in names
                     if n != src_name and self.decode_capable(n)),
                    key=lambda n: (self.decode_load_score(
                        self._snap(n)), n))
                if not cands:
                    asg.ho_busy = False
                    return
                tgt0 = cands[0]
                # resubmits in the tag: a failover between streams
                # must not collide with a stale stage under the
                # same gid on the same target
                tag = ("ho", asg.gid, asg.resubmits)
                cursor = 0
        try:
            try:
                blocks, _n_full = self.replicas[src_name] \
                    .export_kv_prefix(rid, start_block=cursor,
                                      min_blocks=self._handoff_blocks)
            except (ValueError, KeyError, ReplicaError):
                return
            if not blocks:
                return                    # below the chunk threshold
            try:
                self.replicas[tgt0].stage_kv_blocks(tag, blocks)
            except AdmissionFull:
                return                    # target pool full — retry;
                                          # cursor does NOT advance
            except (ReplicaError, KeyError):
                with self._lock:
                    asg.ho_target = asg.ho_tag = None
                    asg.ho_blocks = 0
                return
            raced = False
            with self._lock:
                if asg.done or asg.orphaned:
                    raced = True
                else:
                    asg.ho_target, asg.ho_tag = tgt0, tag
                    asg.ho_blocks = cursor + len(blocks)
            if raced and tgt0 in self.replicas:
                try:
                    self.replicas[tgt0].abort_stage(tag)
                except Exception:
                    pass
        finally:
            with self._lock:
                asg.ho_busy = False

    # ------------------------------------------------- elastic scaling
    def _record_scale(self, direction, name):
        """One scale event in the decision audit (reason scale_up /
        scale_down, gid None — dashboards and the merged cluster trace
        see WHEN the replica set changed next to WHERE requests went)
        plus the per-direction counter in /metrics."""
        entry = None
        if self.audit_enabled:
            entry = {"t": self.clock(), "gid": None, "trace_id": None,
                     "attempt": 0, "policy": self.policy, "chosen": name,
                     "reason": f"scale_{direction}", "scores": {}}
        with self._lock:
            if entry is not None:
                self.audit.append(entry)
            self.audit_counts[f"scale_{direction}"] += 1
            self.scale_events[direction] += 1

    def add_replica(self, replica):
        """Dynamic scale-up: register a new replica and add it to the
        consistent-hash ring — ONLY the keys the new vnodes claim move
        (~K/(N+1)); every other template's home replica, and its hot
        radix chain, stays put (pinned by test). Re-using a retired
        name is allowed (a replaced process). Records a scale_up
        audit event."""
        with self._lock:
            name = replica.name
            if name in self.replicas and name not in self.dead:
                raise ValueError(
                    f"replica {name!r} is already registered and alive")
            self.dead.discard(name)
            self.draining.discard(name)
            self.replicas[name] = replica
            self.roles[name] = str(getattr(replica, "role", "mixed"))
            self._snaps.pop(name, None)
            self.ring.add(name)
        self._record_scale("up", name)
        return name

    def remove_replica(self, name, migrate=True):
        """Graceful scale-down: drain = MIGRATE-then-retire. The
        replica leaves the ring and the placement set immediately (no
        new work lands), every unfinished assignment it holds is
        live-migrated to another replica (``export_slot`` ->
        ``import_slot``: KV blocks + sampler state move, the stream
        resumes mid-decode with zero re-prefill and the delivered
        prefix skipped — greedy token-identical), and only then is the
        handle closed and dropped. ``migrate=False`` (or any migration
        error: target death mid-transfer, rpc timeout, a fault
        injection at the "migration" point) degrades per-assignment to
        the classic failover path — replay from the prompt, never a
        hang, never a double delivery. Returns a drain summary dict
        (protocol.DRAIN_FIELDS)."""
        with self._lock:
            if name not in self.replicas:
                raise KeyError(f"unknown replica {name!r}")
            was_dead = name in self.dead
            src = self.replicas[name]
            if not was_dead:
                self.draining.add(name)
                self.ring.remove(name)
                self._snaps.pop(name, None)
            victims = [asg for asg in self._table.values()
                       if asg.replica == name and not asg.done
                       and not asg.orphaned]
        summary = {"replica": name, "migrated": 0, "failed_over": 0,
                   "orphaned": 0, "expired": 0}
        self.refresh()                    # fresh load scores for targets
        for asg in victims:
            if migrate and not was_dead:
                out = self._migrate_one(asg, name)
            else:
                with self._lock:
                    stuck = (not asg.done and not asg.orphaned
                             and asg.replica == name)
                    if stuck:
                        asg.replica, asg.rid = None, None
                out = None
                if stuck:
                    self._failover_one(asg)
                    out = ("orphaned" if asg.orphaned else
                           "expired" if asg.state == "expired" else
                           "failed_over")
            if out in summary:
                summary[out] += 1
        with self._lock:
            self.draining.discard(name)
            self.dead.discard(name)
            self.replicas.pop(name, None)
            self.roles.pop(name, None)
        try:
            src.close()
        except Exception:
            pass                          # retiring a corpse is fine
        self._record_scale("down", name)
        return summary

    def _migrate_one(self, asg, src_name):
        """Live-migrate ONE assignment off ``src_name``: export the
        slot (KV blocks + decode state leave the source atomically),
        import it on the least-loaded placeable replica (AdmissionFull
        walks the next candidate), and repoint the assignment with the
        delivered-prefix skip — the client stream never notices. ANY
        failure after the export (the testing/fault.py "migration"
        point, a target dying mid-transfer, everyone full) aborts to
        the classic failover fallback: re-submit from the prompt, skip
        the delivered prefix — degraded to a re-prefill, still
        exactly-once. Returns "migrated" | "failed_over" | "orphaned" |
        "expired" | "skipped"."""
        from ..testing import fault
        src = self.replicas[src_name]
        with self._lock:
            if asg.done or asg.orphaned or asg.replica != src_name \
                    or asg.rid is None:
                return "skipped"
            rid = asg.rid
        if asg.ho_tag is not None:
            # a drain-migration exports the FULL payload (skip 0) —
            # any streamed prefix staged for the handoff path is
            # stale the moment the session moves
            self._drop_stage(asg)
        if asg.hg_rid is not None:
            # a speculative duplicate must not chase a moving session
            self._drop_hedge(asg)
        # final harvest first: a request that FINISHED on the engine but
        # was not yet collected needs its tokens drained, not a
        # migration (exporting it would fail and the fallback would
        # wastefully replay a completed request elsewhere)
        try:
            new, done, state = src.harvest(rid)
        except Exception:
            new, done, state = None, False, None
        with self._lock:
            if new is not None and (asg.replica, asg.rid) == (src_name,
                                                              rid):
                if asg.skip:
                    drop = min(asg.skip, len(new))
                    asg.skip -= drop
                    new = new[drop:]
                asg.tokens.extend(new)
                if done:
                    asg.done, asg.state = True, state
                    return "skipped"
            if asg.done or asg.orphaned or (asg.replica, asg.rid) != \
                    (src_name, rid):
                return "skipped"
            # detach NOW: a concurrent harvest that raced the export
            # discards its batch (epoch mismatch) exactly like failover
            asg.replica, asg.rid = None, None
        attempt = asg.resubmits + 2
        tgt_name = rid2 = None
        try:
            state = src.export_slot(rid)
            # the chaos lever: PADDLE_FI_AT_POINT=migration kills the
            # transfer exactly here — state is off the source, not yet
            # on any target (the worst moment)
            fault.inject("migration")
            if asg.kw.get("deadline_s") is not None:
                # remaining budget from the PRISTINE submit-time deadline
                # (like _failover_one) — the exported value is already
                # the remainder from any prior migration, so subtracting
                # elapsed-since-submit from IT would double-count every
                # leg before this one
                remaining = asg.kw["deadline_s"] - (self.clock()
                                                    - asg.t_submit)
                if remaining <= 0:
                    with self._lock:
                        asg.done, asg.state = True, "expired"
                    return "expired"
                state["deadline_s"] = remaining
            state["attempt"] = attempt
            # role check (pinned by the drain test): a session that
            # still owes prefill work may only land prefill-capable —
            # a decode-only replica would starve it forever. A
            # prompt-complete session goes to the decode pool, scored
            # by resident-session pressure (no queue term).
            need_prefill = (int(state.get("pf_left", 0)) > 0
                            or int(state.get("nt", 0)) == 0)
            with self._lock:
                if need_prefill:
                    order = sorted(
                        (n for n in self.placeable_names()
                         if n != src_name and self.prefill_capable(n)),
                        key=lambda n: (self.load_score(self._snap(n)),
                                       n))
                else:
                    order = sorted(
                        (n for n in self.placeable_names()
                         if n != src_name and self.decode_capable(n)),
                        key=lambda n: (self.decode_load_score(
                            self._snap(n)), n))
            last_full = None
            for cand in order:
                try:
                    rid2 = self.replicas[cand].import_slot(state)
                except AdmissionFull as e:
                    last_full = e
                    continue
                tgt_name = cand
                break
            if tgt_name is None:
                raise last_full if last_full is not None else \
                    NoReplicaError("no placeable replica to migrate to")
        except Exception:
            with self._lock:
                self.migration_aborts_total += 1
                stuck = not asg.done and not asg.orphaned
            if stuck:
                self._failover_one(asg)
            with self._lock:
                return ("orphaned" if asg.orphaned else
                        "expired" if asg.state == "expired" else
                        "failed_over")
        with self._lock:
            if asg.gid in self._table and not asg.done:
                asg.skip = len(asg.tokens)
                asg.replica, asg.rid = tgt_name, rid2
                asg.t_placed = self.clock()
                asg.resubmits += 1
                self.migrations_total += 1
                stray = None
            else:                         # released/finished meanwhile
                stray = self.replicas.get(tgt_name)
        if stray is not None:
            stray.release(rid2)
            return "skipped"
        self._record_decision(asg, tgt_name, "migrated", {}, attempt)
        return "migrated"

    def scale_status(self):
        """The /admin/scale payload's router half (the gateway folds in
        the autoscaler's bounds)."""
        with self._lock:
            roles = {"prefill": 0, "decode": 0, "mixed": 0}
            for n in self.alive_names():
                roles[self.roles.get(n, "mixed")] += 1
            return {"replicas_alive": len(self.alive_names()),
                    "replicas_total": len(self.replicas),
                    "draining": sorted(self.draining),
                    "migrations_total": self.migrations_total,
                    "migration_aborts_total": self.migration_aborts_total,
                    "scale_events_up": self.scale_events["up"],
                    "scale_events_down": self.scale_events["down"],
                    "roles": roles,
                    "handoffs_total": self.handoffs_total}

    # ------------------------------------------------------- aggregation
    def metrics_prometheus(self):
        """Cluster exposition: each alive replica's engine exposition
        with a ``replica`` label injected on every sample, the GATEWAY
        PROCESS's own runtime registry (HTTP latency histograms, rpc
        client latency) under ``replica="gateway"``, the router's
        placement-decision counters, and the router gauges (replica
        I/O outside the lock). One scrape shows the whole cluster.

        Note for in-process (LocalReplica) clusters: the gateway and
        its replicas share one process, so process-global runtime
        families legitimately appear under both a replica label and
        the gateway label — distinct series, one HELP/TYPE."""
        with self._lock:
            names = self.alive_names()
        lines = []
        seen_meta = set()

        def _append(text, label):
            for ln in _relabel(text, label):
                if ln.startswith("#"):
                    # ONE HELP/TYPE line per family across the whole
                    # cluster: Prometheus rejects a second HELP line
                    # for the same metric name, so duplicates from
                    # replica 2..N are dropped here
                    parts = ln.split(None, 3)
                    key = tuple(parts[:3])
                    if key in seen_meta:
                        continue
                    seen_meta.add(key)
                lines.append(ln)

        for name in names:
            try:
                text = self.replicas[name].metrics_prometheus()
            except ReplicaError:
                self.mark_dead(name)
                continue
            _append(text, name)
        # the gateway process's own runtime registry: HTTP endpoint
        # latency histograms (gateway.py records them per
        # endpoint+status) and the rpc client's call latency — the
        # front-end's accept/parse/stream time was invisible when
        # /metrics only relabeled engine expositions
        from ..inference.telemetry import runtime_prometheus
        _append("\n".join(runtime_prometheus()) + "\n", "gateway")
        with self._lock:
            name = "paddle_gateway_route_decisions_total"
            lines.append(f"# HELP {name} placements by audit reason "
                         "(router decision audit ring)")
            lines.append(f"# TYPE {name} counter")
            for reason in AUDIT_REASONS:
                lines.append(f'{name}{{reason="{reason}"}} '
                             f"{self.audit_counts[reason]}")
            # elastic control-plane counters (zero-initialized like the
            # decision counters: the label set is discoverable before
            # any scale event — pinned by check_metrics_surface)
            name = "paddle_gateway_scale_events_total"
            lines.append(f"# HELP {name} replica-set changes by "
                         "direction (autoscaler or /admin/scale)")
            lines.append(f"# TYPE {name} counter")
            for d in ("up", "down"):
                lines.append(f'{name}{{direction="{d}"}} '
                             f"{self.scale_events[d]}")
            # circuit-breaker state machine traffic (zero-initialized:
            # the label set is discoverable before any gray failure)
            name = "paddle_gateway_breaker_transitions_total"
            lines.append(f"# HELP {name} circuit breaker state "
                         "transitions by target state")
            lines.append(f"# TYPE {name} counter")
            for to in ("open", "half_open", "closed"):
                lines.append(f'{name}{{to="{to}"}} '
                             f"{self.breaker_transitions[to]}")
        # per-replica gray-failure verdicts + breaker states (encoded
        # gauges: 0 healthy/closed, 1 suspect/half_open, 2
        # degraded/open); health_status takes the lock itself
        status = self.health_status()
        vmap = {"healthy": 0, "suspect": 1, "degraded": 2}
        bmap = {"closed": 0, "half_open": 1, "open": 2}
        name = "paddle_gateway_replica_health_state"
        lines.append(f"# HELP {name} gray-failure verdict "
                     "(0=healthy 1=suspect 2=degraded)")
        lines.append(f"# TYPE {name} gauge")
        for n in sorted(status):
            lines.append(f'{name}{{replica="{n}"}} '
                         f'{vmap[status[n]["verdict"]]}')
        name = "paddle_gateway_breaker_state"
        lines.append(f"# HELP {name} circuit breaker state "
                     "(0=closed 1=half_open 2=open)")
        lines.append(f"# TYPE {name} gauge")
        for n in sorted(status):
            lines.append(f'{name}{{replica="{n}"}} '
                         f'{bmap[status[n]["breaker"]]}')
        with self._lock:
            gauges = (
                ("paddle_gateway_replicas_alive", "gauge",
                 len(self.alive_names()), "replicas currently routable"),
                ("paddle_gateway_replicas_total", "gauge",
                 len(self.replicas), "replicas configured"),
                ("paddle_gateway_requests_routed_total", "counter",
                 self.submits_total, "requests placed by the router"),
                ("paddle_gateway_failovers_total", "counter",
                 self.failovers_total,
                 "in-flight re-submissions after a replica death"),
                ("paddle_gateway_migrations_total", "counter",
                 self.migrations_total,
                 "live sessions moved replica-to-replica (drain)"),
                ("paddle_gateway_migration_aborts_total", "counter",
                 self.migration_aborts_total,
                 "migrations aborted mid-transfer -> classic failover"),
                ("paddle_gateway_handoffs_total", "counter",
                 self.handoffs_total,
                 "prefill->decode KV handoffs completed (disagg)"),
                ("paddle_gateway_snapshot_version_mismatches_total",
                 "counter", self.version_mismatches,
                 "snapshots refused for schema_version drift"),
                ("paddle_gateway_hedges_total", "counter",
                 self.hedges_total,
                 "speculative duplicate dispatches (greedy only)"),
                ("paddle_gateway_hedge_wins_total", "counter",
                 self.hedge_wins_total,
                 "hedge legs that beat the original to first token"),
                ("paddle_gateway_retry_budget_exhausted_total",
                 "counter", self.retry_budget_exhausted_total,
                 "retry/hedge attempts that found the token bucket "
                 "empty"),
                ("paddle_gateway_retry_budget_tokens", "gauge",
                 round(self._retry_tokens, 4),
                 "retry/hedge token bucket level"))
        for gname, typ, val, help_ in gauges:
            lines.append(f"# HELP {gname} {help_}")
            lines.append(f"# TYPE {gname} {typ}")
            lines.append(f"{gname} {val}")
        return "\n".join(lines) + "\n"


def _relabel(text, replica):
    """Inject ``replica="name"`` into every sample line of one
    replica's Prometheus exposition; HELP/TYPE comments pass through
    (the caller de-duplicates them across replicas — Prometheus rejects
    a repeated HELP line for one family)."""
    out = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            out.append(ln)
            continue
        name_part, _, value = ln.rpartition(" ")
        if "{" in name_part:
            fam, rest = name_part.split("{", 1)
            out.append(f'{fam}{{replica="{replica}",{rest} {value}')
        else:
            out.append(f'{name_part}{{replica="{replica}"}} {value}')
    return out
