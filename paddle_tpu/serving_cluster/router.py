"""Placement-sensitive request router over N replicas.

The router turns replica handles (replica.py) into one submit/harvest
surface with three policies (``PADDLE_ROUTER_POLICY``):

  * ``round_robin`` — arrival order over alive replicas (the A/B
    baseline: placement-blind).
  * ``least_loaded`` — minimize a load score read from each replica's
    ``telemetry_snapshot()``: ``queue_depth + busy_slots +
    num_slots * kv_used_frac`` (queue pressure, slot pressure, pool
    headroom — the three admission bottlenecks the engine exposes).
  * ``prefix_affinity`` (default) — consistent-hash the FIRST
    ``prefill_cap``-aligned prompt block onto a replica ring, so every
    request sharing a template lands where that template's radix chain
    is already hot (prefix_cache.py); prompts shorter than one block
    carry no shareable block and fall back to least-loaded, and a
    SATURATED owner (queue_depth >= ``PADDLE_ROUTER_SPILL_DEPTH``)
    spills to least-loaded — affinity must never become head-of-line
    blocking. Honesty note: affinity only pays at hit-rate > 0; on
    no-template traffic it IS least-loaded with extra hashing.

Replica death is a first-class path, not an exception trail:
``check_health()`` (the gateway's heartbeat loop) marks a replica dead
when its heartbeat age passes ``PADDLE_GATEWAY_HB_DEAD_S`` and its
liveness probe fails, removes it from the hash ring (consistent
hashing: only ITS keys move), and re-submits every one of its
unfinished assignments elsewhere. Re-submission is idempotent by
gateway request id and replays from the prompt; the assignment
remembers how many tokens were already DELIVERED downstream and skips
that many from the replacement stream — greedy decoding makes the
replayed prefix token-identical, so the client's stream is seamless
(sampled mode re-draws its per-request seed on the new engine and is
documented as NOT replay-identical).

Snapshots are trusted only at the pinned ``SNAPSHOT_SCHEMA_VERSION``:
a replica reporting an unknown version is excluded from load scoring
(counted in ``version_mismatches``) instead of being silently misread.

Every placement is AUDITED: the router records WHY each request landed
where it did — policy, per-candidate load scores, chosen replica, and
a reason from ``AUDIT_REASONS`` — in a bounded ring
(``PADDLE_ROUTER_AUDIT_RING``, default 2048), with per-reason counters
in the ``/metrics`` exposition
(``paddle_gateway_route_decisions_total{reason=...}``) and the full
entries merged into the cluster Perfetto export (trace.py). Trace
context rides along: ``submit`` mints (or accepts) a ``trace_id`` and
threads it through every replica submit — failover re-submits carry
the SAME trace id with an incremented attempt, so a kill-drill stream
yields one joined trace.
"""
from __future__ import annotations

import bisect
import hashlib
import os
import threading
import time
import uuid
from collections import deque

from ..inference.serving import AdmissionFull
from ..inference.telemetry import SNAPSHOT_SCHEMA_VERSION
from .replica import ReplicaError

__all__ = ["HashRing", "Router", "NoReplicaError", "POLICIES",
           "AUDIT_REASONS"]

POLICIES = ("prefix_affinity", "least_loaded", "round_robin")

# every reason a placement decision can record (pinned by
# tools/check_metrics_surface.py — the audit counters' label set must
# not drift): affinity_hit = consistent-hash owner took it, spill =
# saturated/shedding owner overflowed to least-loaded, least_loaded /
# round_robin = the policy's own choice, failover = re-submit after a
# replica death, orphaned = failover found nowhere to go
AUDIT_REASONS = ("affinity_hit", "least_loaded", "round_robin", "spill",
                 "failover", "orphaned")


class NoReplicaError(ReplicaError):
    """Every replica is dead/unreachable — the gateway maps this to 503
    (service unavailable), distinct from 429 backpressure."""


def _hash64(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring with virtual nodes: add/remove a replica
    moves only the keys it owns (~K/N of them), which is exactly the
    prefix-affinity requirement — a replica death must not reshuffle
    every template's home and cold-start every other radix store."""

    def __init__(self, vnodes=64):
        self.vnodes = int(vnodes)
        self._points = []                 # sorted [(hash, name)]
        self.names = set()

    def add(self, name):
        if name in self.names:
            return
        self.names.add(name)
        for i in range(self.vnodes):
            h = _hash64(f"{name}#{i}".encode())
            bisect.insort(self._points, (h, name))

    def remove(self, name):
        if name not in self.names:
            return
        self.names.discard(name)
        self._points = [(h, n) for h, n in self._points if n != name]

    def owner(self, key: bytes):
        """The replica owning ``key`` (first point clockwise), or None
        on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, (_hash64(key), b""))
        if i == len(self._points):
            i = 0
        return self._points[i][1]


def _locked(fn):
    """Serialize a Router method on the instance lock (see the class
    docstring's thread-safety contract). RLock: harvest -> mark_dead ->
    _place nest on the same thread."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return wrapper


class _Assignment:
    __slots__ = ("gid", "request_id", "prompt", "kw", "replica", "rid",
                 "tokens", "skip", "done", "state", "resubmits",
                 "t_submit", "orphaned", "failed", "dup_returns",
                 "trace_id")

    def __init__(self, gid, request_id, prompt, kw, replica, rid,
                 t_submit, trace_id=None):
        self.gid = gid
        self.request_id = request_id
        self.trace_id = trace_id          # cluster trace context
        self.prompt = prompt
        self.kw = kw
        self.replica = replica            # None = placement in flight
        self.rid = rid
        self.tokens = []                  # full de-duplicated history:
        self.skip = 0                     # replayed prefix to drop
        self.done = False                 # every harvested token lands
        self.state = "running"            # here exactly once, so N
        self.resubmits = 0                # concurrent readers can each
        self.t_submit = t_submit          # stream from their own cursor
        self.orphaned = False
        self.failed = None                # placement exception, if any
        self.dup_returns = 0              # idempotent-retry handouts


class Router:
    """See the module docstring. All waits are the caller's: submit and
    harvest are single bounded calls; health checking is explicit
    (``check_health``), so a virtual-clock bench or a deterministic test
    can drive the whole failure path without sleeping.

    Thread-safety: the gateway drives this from multiple thread-pool
    executor threads (one per in-flight HTTP request) plus the health
    loop. ONE reentrant lock guards all router state (gid allocation,
    the assignment table, the ring, the dead set, snapshots) — but
    replica I/O (submit/harvest/snapshot/probe over a lock or rpc) is
    ALWAYS performed outside it, so a frozen replica stalls only the
    calls touching it, never the whole front-end. Races with failover
    are resolved by re-checking the assignment's (replica, rid) epoch
    after the I/O: a harvest that lost the race discards its batch
    (the replacement replays those tokens), and each harvested token
    lands in the assignment's history exactly once."""

    def __init__(self, replicas, policy=None, spill_depth=None,
                 hb_dead_s=None, snap_max_age_s=None, clock=None,
                 audit_ring=None):
        self.replicas = {r.name: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica names must be unique")
        self.policy = policy or os.environ.get("PADDLE_ROUTER_POLICY",
                                               "prefix_affinity")
        if self.policy not in POLICIES:
            raise ValueError(f"unknown router policy {self.policy!r} "
                             f"(choose from {POLICIES})")
        self.spill_depth = int(
            spill_depth if spill_depth is not None
            else os.environ.get("PADDLE_ROUTER_SPILL_DEPTH", "4"))
        self.hb_dead_s = float(
            hb_dead_s if hb_dead_s is not None
            else os.environ.get("PADDLE_GATEWAY_HB_DEAD_S", "2.0"))
        self.snap_max_age_s = float(
            snap_max_age_s if snap_max_age_s is not None
            else os.environ.get("PADDLE_ROUTER_SNAP_AGE_S", "0.25"))
        self.clock = clock or time.monotonic
        self._lock = threading.RLock()
        self.ring = HashRing()
        for name in sorted(self.replicas):
            self.ring.add(name)
        self.dead = set()
        self._snaps = {}                  # name -> (snapshot, t)
        self._rr = 0                      # round-robin cursor
        self._gid = 0
        self._table = {}                  # gid -> _Assignment
        self._by_request_id = {}          # idempotency key -> gid
        self.submits_total = 0
        self.failovers_total = 0
        self.version_mismatches = 0
        self._prefill_cap = None
        # placement decision audit: bounded ring of WHY each request
        # landed where it did, plus per-reason counters (exposed in
        # /metrics and merged into the cluster Perfetto export)
        ar = int(audit_ring if audit_ring is not None
                 else os.environ.get("PADDLE_ROUTER_AUDIT_RING", "2048"))
        if ar < 0:
            raise ValueError(f"audit ring must be >= 0, got {ar}")
        # 0 disables the ring (no per-decision entry is built or
        # stored) but the per-reason counters stay — they're pinned in
        # /metrics by tools/check_metrics_surface.py and cost one dict
        # increment per placement
        self.audit_enabled = ar > 0
        self.audit = deque(maxlen=max(ar, 1))
        self.audit_counts = {r: 0 for r in AUDIT_REASONS}

    # -------------------------------------------------------- snapshots
    def alive_names(self):
        return [n for n in sorted(self.replicas) if n not in self.dead]

    def refresh(self, force=False):
        """Pull each alive replica's telemetry snapshot (the routing
        payload), at most once per ``snap_max_age_s`` unless forced. A
        replica that errors here is NOT declared dead — one flaky
        snapshot must not drain a healthy replica; its stale snapshot
        is dropped (it scores worst until it answers again) and the
        death verdict stays with check_health's heartbeat + liveness
        probe (and with actual failed submits/harvests).

        Deliberately NOT @_locked around the replica I/O: when the
        health loop refreshes, a frozen rpc worker must stall only ITS
        snapshot call, never every submit/harvest waiting on the
        router lock. (A submit-path refresh still runs under the
        caller's RLock frame — the short rpc snapshot timeout bounds
        that case.)"""
        now = self.clock()
        with self._lock:
            todo = []
            for name in self.alive_names():
                got = self._snaps.get(name)
                if force or got is None \
                        or now - got[1] > self.snap_max_age_s:
                    todo.append(name)
        fetched = {}
        for name in todo:
            try:
                fetched[name] = self.replicas[name].snapshot()
            except ReplicaError:
                fetched[name] = None
        with self._lock:
            for name, snap in fetched.items():
                if name in self.dead:
                    continue
                if snap is None:
                    self._snaps.pop(name, None)
                elif snap.get("schema_version") != \
                        SNAPSHOT_SCHEMA_VERSION:
                    # unknown payload: refuse to score it (drop any
                    # stale cached one too) rather than misread it
                    self.version_mismatches += 1
                    self._snaps.pop(name, None)
                else:
                    self._snaps[name] = (snap, now)
                    self._prefill_cap = snap["prefill_cap"]

    def _snap(self, name):
        got = self._snaps.get(name)
        return got[0] if got else None

    @staticmethod
    def load_score(snap):
        """queue pressure + slot pressure + pool pressure, one number.
        Missing snapshot scores worst — never prefer a replica you know
        nothing about over one you do."""
        if snap is None:
            return float("inf")
        busy = snap["num_slots"] - snap["slots_free"]
        score = snap["queue_depth"] + busy
        kv = snap.get("kv_blocks")
        if kv and kv["kv_blocks_total"]:
            score += snap["num_slots"] * (kv["kv_blocks_used"]
                                          / kv["kv_blocks_total"])
        return score

    # -------------------------------------------------------- placement
    def _least_loaded(self, names):
        return min(names, key=lambda n: (self.load_score(self._snap(n)),
                                         n))

    def prefix_key(self, prompt):
        """The affinity key: the first ``prefill_cap``-aligned prompt
        block (bytes), or None when the prompt is shorter than one
        block (nothing shareable to be affine about)."""
        cap = self._prefill_cap
        if cap is None or len(prompt) < cap:
            return None
        return ",".join(str(int(t)) for t in prompt[:cap]).encode()

    def _choose(self, prompt, names):
        """One policy choice over ``names``: returns ``(name, reason)``
        with reason from AUDIT_REASONS — the decision audit records WHY
        alongside WHERE."""
        if self.policy == "round_robin":
            self._rr += 1
            return names[self._rr % len(names)], "round_robin"
        if self.policy == "least_loaded":
            return self._least_loaded(names), "least_loaded"
        key = self.prefix_key(prompt)
        if key is None:
            return self._least_loaded(names), "least_loaded"
        owner = self.ring.owner(key)
        if owner not in names:
            return self._least_loaded(names), "least_loaded"
        snap = self._snap(owner)
        if snap is not None and snap["queue_depth"] >= self.spill_depth:
            # saturation spill: the hot replica keeps its cache, the
            # overflow goes wherever there is headroom
            return self._least_loaded(names), "spill"
        return owner, "affinity_hit"

    def _record_decision(self, asg, chosen, reason, scores, attempt):
        """Append one audit entry (bounded ring) + bump its reason
        counter. JSON-able by construction (the cluster trace export
        and tools/slo_report.py both consume entries verbatim):
        unknown-snapshot scores (inf) are recorded as None. Ring size
        0 skips the entry entirely; the reason counter always bumps."""
        entry = None
        if self.audit_enabled:
            entry = {
                "t": self.clock(),
                "gid": asg.gid,
                "trace_id": asg.trace_id,
                "attempt": int(attempt),
                "policy": self.policy,
                "chosen": chosen,
                "reason": reason,
                "scores": {n: (None if s == float("inf")
                               else round(s, 4))
                           for n, s in scores.items()},
            }
        with self._lock:
            if entry is not None:
                self.audit.append(entry)
            self.audit_counts[reason] += 1

    # ------------------------------------------------------- submit path
    def submit(self, prompt, request_id=None, trace_id=None, **kw):
        """Route one request; returns the gateway-global id (gid).
        Idempotent on ``request_id``: a repeat — concurrent or later,
        while the original assignment is live — returns the existing
        gid without re-running anything (the gid is RESERVED before
        the placement I/O, so two simultaneous retries cannot race
        into two engine submissions). AdmissionFull propagates only
        when EVERY alive replica sheds (honest cluster-wide
        backpressure); a replica that dies mid-submit is failed over
        transparently.

        ``trace_id`` is the cluster trace context (the gateway mints
        one per HTTP request, honoring an inbound ``X-Request-Id``);
        direct callers that pass none get a minted id, so every
        placement is traceable. The id survives failover re-submits
        (attempt increments), joining the request's spans across
        replicas."""
        prompt = [int(t) for t in prompt]
        if trace_id is None:
            trace_id = uuid.uuid4().hex
        with self._lock:
            if request_id is not None \
                    and request_id in self._by_request_id:
                gid = self._by_request_id[request_id]
                got = self._table.get(gid)
                if got is not None:
                    got.dup_returns += 1
                return gid
            self._gid += 1
            gid = f"req-{self._gid}"
            asg = _Assignment(gid, request_id, prompt, kw, None, None,
                              self.clock(), trace_id=str(trace_id))
            self._table[gid] = asg
            if request_id is not None:
                self._by_request_id[request_id] = gid
            self.submits_total += 1
        self.refresh()
        try:
            name, rid = self._place(prompt, kw, asg=asg, attempt=1)
        except Exception as e:
            with self._lock:
                # unwind the reservation — unless a concurrent
                # idempotent retry already took this gid home, in
                # which case the entry stays and carries the failure
                # (its harvest re-raises e, so 429 stays 429 instead
                # of decaying into a 404 for the duplicate; the
                # duplicate's release drops the entry)
                if request_id is not None:
                    self._by_request_id.pop(request_id, None)
                if asg.dup_returns:
                    asg.failed = e
                else:
                    self._table.pop(gid, None)
            raise
        with self._lock:
            asg.replica, asg.rid = name, rid
            # the chosen replica may have been declared dead between
            # our successful engine submit and this bookkeeping write
            # — mark_dead's drain skipped the still-placement-pending
            # assignment, so the failover is OURS to run
            raced_death = name in self.dead and not asg.done
            if raced_death:
                asg.replica, asg.rid = None, None
        if raced_death:
            self._failover_one(asg)
        return gid

    def _place(self, prompt, kw, exclude=(), asg=None, attempt=1,
               reason_override=None):
        """One placement attempt over the alive set: policy choice
        first, then the remaining candidates by load on AdmissionFull
        (spill), marking dead anything that errors. The replica submit
        itself runs OUTSIDE the router lock (a frozen replica must not
        stall unrelated requests). Raises the LAST AdmissionFull when
        everyone sheds. A successful placement is recorded in the
        decision audit (reason from the policy choice; ``spill`` once a
        shed forced a retry elsewhere; ``reason_override`` stamps the
        failover path)."""
        last_full = None
        tried = set(exclude)
        shed = False
        while True:
            with self._lock:
                names = [n for n in self.alive_names()
                         if n not in tried]
                if names:
                    name, reason = self._choose(prompt, names)
                    # the per-candidate score dict exists only for the
                    # audit entry — skip it when the ring is off
                    scores = ({n: self.load_score(self._snap(n))
                               for n in names}
                              if self.audit_enabled else {})
                else:
                    name = None
            if name is None:
                if last_full is not None:
                    raise last_full
                raise NoReplicaError("no alive replica to place on")
            tried.add(name)
            try:
                rid = self.replicas[name].submit(
                    prompt,
                    trace_id=None if asg is None else asg.trace_id,
                    attempt=attempt, **kw)
            except AdmissionFull as e:
                last_full = e
                shed = True               # the next landing is a spill
            except ReplicaError:
                self.mark_dead(name)
            else:
                if asg is not None:
                    self._record_decision(
                        asg, name,
                        reason_override or ("spill" if shed else reason),
                        scores, attempt)
                return name, rid

    # ------------------------------------------------------ harvest path
    def harvest(self, gid, cursor=None):
        """Incremental harvest for one gateway request: ``(new_tokens,
        done, state)``. Every harvested token lands in the
        assignment's history exactly once; ``cursor=None`` returns the
        tokens appended since the last cursorless call (single-reader
        delta semantics), an explicit integer cursor returns
        ``history[cursor:]`` so concurrent readers of one gid (an
        idempotent client retry) each see the complete stream. A
        replica death here triggers the failover re-submit and returns
        an empty batch (the stream stalls one poll interval, never
        errors); the replayed prefix is skipped so the history gets
        each token once. KeyError for an unknown/released gid."""
        with self._lock:
            asg = self._table[gid]
            base = len(asg.tokens) if cursor is None else int(cursor)
            if asg.failed is not None:
                raise asg.failed          # duplicate of a shed submit:
            if asg.done:                  # 429 stays 429, never a 404
                return list(asg.tokens[base:]), True, asg.state
            if asg.orphaned:
                raise NoReplicaError(
                    f"{gid}: no alive replica to fail over to")
            epoch = (asg.replica, asg.rid)
            rep = (None if asg.replica is None
                   else self.replicas[asg.replica])
            if rep is None:               # failover placement in flight
                return list(asg.tokens[base:]), False, "running"
        try:
            new, done, state = rep.harvest(epoch[1])
        except ReplicaError:
            self.mark_dead(epoch[0])
            with self._lock:
                # mark_dead no-ops when the replica was ALREADY dead
                # (e.g. it died between a submit placing here and the
                # bookkeeping write) — if the assignment still points
                # at the corpse, the failover is ours to run
                stuck = (not asg.done and not asg.orphaned
                         and (asg.replica, asg.rid) == epoch)
                if stuck:
                    asg.replica, asg.rid = None, None
            if stuck:
                self._failover_one(asg)
            with self._lock:
                return list(asg.tokens[base:]), False, "running"
        with self._lock:
            if (asg.replica, asg.rid) != epoch:
                # failover raced this harvest: DISCARD the batch — the
                # replacement replays it (skip was set against the
                # history length, which this batch never joined)
                return list(asg.tokens[base:]), False, "running"
            if asg.skip:
                drop = min(asg.skip, len(new))
                asg.skip -= drop
                new = new[drop:]
            asg.tokens.extend(new)
            if done:
                asg.done, asg.state = True, state
            return list(asg.tokens[base:]), done, state

    @_locked
    def poll(self, gid):
        asg = self._table.get(gid)
        if asg is None:
            return None
        return {"gid": gid, "replica": asg.replica, "done": asg.done,
                "state": asg.state, "delivered": len(asg.tokens),
                "resubmits": asg.resubmits, "trace_id": asg.trace_id,
                "attempt": asg.resubmits + 1}

    def trace_id_of(self, gid):
        """The trace id riding assignment ``gid`` (None once
        released). The gateway re-reads this after submit: an
        idempotent repeat returns the ORIGINAL submission's gid, and
        the response must echo the trace id the engine spans and the
        decision audit actually carry — not whatever fresh id the
        retry arrived with."""
        with self._lock:
            got = self._table.get(gid)
            return None if got is None else got.trace_id

    def release(self, gid):
        """Forget a finished/abandoned request (client disconnect).
        NOTE: with concurrent readers of one gid (idempotent retry),
        the first release drops the assignment for all of them — the
        gateway maps the survivors' KeyError to 404."""
        with self._lock:
            asg = self._table.pop(gid, None)
            if asg is None:
                return
            if asg.request_id is not None:
                self._by_request_id.pop(asg.request_id, None)
            rep = None
            if not asg.done and not asg.orphaned \
                    and asg.replica is not None:
                rep = self.replicas.get(asg.replica)
        if rep is not None:
            rep.release(asg.rid)

    # ----------------------------------------------------------- health
    def check_health(self):
        """Heartbeat sweep: a replica whose heartbeat age passed
        ``hb_dead_s`` gets ONE bounded liveness probe (outside the
        router lock); failure = dead = drain + re-route. Returns the
        names newly marked dead."""
        with self._lock:
            suspects = [n for n in self.alive_names()
                        if self.replicas[n].heartbeat_age()
                        > self.hb_dead_s]
        died = []
        for name in suspects:
            if self.replicas[name].alive:  # probe refreshes the beat
                continue
            self.mark_dead(name)
            died.append(name)
        return died

    def mark_dead(self, name):
        """Death IS drain: remove from the ring (only its keys move),
        then re-submit every unfinished assignment it held — idempotent
        per assignment (each is re-placed exactly once per death), with
        the delivered-history length remembered so the replayed greedy
        prefix is skipped, not double-streamed. Re-placement I/O runs
        outside the lock; until it lands the assignment's replica is
        None and harvests return empty batches. A deadline_s request
        fails over with its REMAINING budget (measured from the
        original submit) — an already-expired one goes straight to the
        expired state instead of restarting its clock."""
        with self._lock:
            if name in self.dead:
                return
            self.dead.add(name)
            self.ring.remove(name)
            self._snaps.pop(name, None)
            victims = [asg for asg in self._table.values()
                       if asg.replica == name and not asg.done
                       and not asg.orphaned]
            for asg in victims:
                asg.replica, asg.rid = None, None
        for asg in victims:
            self._failover_one(asg)

    def _failover_one(self, asg):
        """Re-place ONE assignment whose replica is gone (the caller
        already nulled its replica/rid under the lock). Deadline
        requests fail over with their REMAINING budget; a released-
        while-draining assignment (client disconnect racing the drain)
        gets its stray replacement submission released instead of
        leaking a tracked engine record forever."""
        kw = dict(asg.kw)
        if kw.get("deadline_s") is not None:
            remaining = kw["deadline_s"] - (self.clock()
                                            - asg.t_submit)
            if remaining <= 0:
                with self._lock:
                    asg.done, asg.state = True, "expired"
                return
            kw["deadline_s"] = remaining
        # same trace id, NEXT attempt: the re-submitted stream joins
        # the original's trace (resubmits bumps only after placement
        # lands, so attempt = prior resubmits + this one + 1)
        attempt = asg.resubmits + 2
        try:
            new_name, rid = self._place(asg.prompt, kw, asg=asg,
                                        attempt=attempt,
                                        reason_override="failover")
        except (AdmissionFull, NoReplicaError):
            # nowhere to go RIGHT NOW: orphan it honestly; the
            # gateway surfaces 503/429 instead of hanging
            with self._lock:
                asg.orphaned = True
                asg.state = "orphaned"
            self._record_decision(asg, None, "orphaned", {}, attempt)
            return
        with self._lock:
            if asg.gid in self._table and not asg.done:
                asg.skip = len(asg.tokens)
                asg.replica, asg.rid = new_name, rid
                asg.resubmits += 1
                self.failovers_total += 1
                stray = None
            else:                         # released/finished meanwhile
                stray = self.replicas.get(new_name)
        if stray is not None:
            stray.release(rid)

    # ------------------------------------------------------- aggregation
    def metrics_prometheus(self):
        """Cluster exposition: each alive replica's engine exposition
        with a ``replica`` label injected on every sample, the GATEWAY
        PROCESS's own runtime registry (HTTP latency histograms, rpc
        client latency) under ``replica="gateway"``, the router's
        placement-decision counters, and the router gauges (replica
        I/O outside the lock). One scrape shows the whole cluster.

        Note for in-process (LocalReplica) clusters: the gateway and
        its replicas share one process, so process-global runtime
        families legitimately appear under both a replica label and
        the gateway label — distinct series, one HELP/TYPE."""
        with self._lock:
            names = self.alive_names()
        lines = []
        seen_meta = set()

        def _append(text, label):
            for ln in _relabel(text, label):
                if ln.startswith("#"):
                    # ONE HELP/TYPE line per family across the whole
                    # cluster: Prometheus rejects a second HELP line
                    # for the same metric name, so duplicates from
                    # replica 2..N are dropped here
                    parts = ln.split(None, 3)
                    key = tuple(parts[:3])
                    if key in seen_meta:
                        continue
                    seen_meta.add(key)
                lines.append(ln)

        for name in names:
            try:
                text = self.replicas[name].metrics_prometheus()
            except ReplicaError:
                self.mark_dead(name)
                continue
            _append(text, name)
        # the gateway process's own runtime registry: HTTP endpoint
        # latency histograms (gateway.py records them per
        # endpoint+status) and the rpc client's call latency — the
        # front-end's accept/parse/stream time was invisible when
        # /metrics only relabeled engine expositions
        from ..inference.telemetry import runtime_prometheus
        _append("\n".join(runtime_prometheus()) + "\n", "gateway")
        with self._lock:
            name = "paddle_gateway_route_decisions_total"
            lines.append(f"# HELP {name} placements by audit reason "
                         "(router decision audit ring)")
            lines.append(f"# TYPE {name} counter")
            for reason in AUDIT_REASONS:
                lines.append(f'{name}{{reason="{reason}"}} '
                             f"{self.audit_counts[reason]}")
        with self._lock:
            gauges = (
                ("paddle_gateway_replicas_alive", "gauge",
                 len(self.alive_names()), "replicas currently routable"),
                ("paddle_gateway_replicas_total", "gauge",
                 len(self.replicas), "replicas configured"),
                ("paddle_gateway_requests_routed_total", "counter",
                 self.submits_total, "requests placed by the router"),
                ("paddle_gateway_failovers_total", "counter",
                 self.failovers_total,
                 "in-flight re-submissions after a replica death"),
                ("paddle_gateway_snapshot_version_mismatches_total",
                 "counter", self.version_mismatches,
                 "snapshots refused for schema_version drift"))
        for gname, typ, val, help_ in gauges:
            lines.append(f"# HELP {gname} {help_}")
            lines.append(f"# TYPE {gname} {typ}")
            lines.append(f"{gname} {val}")
        return "\n".join(lines) + "\n"


def _relabel(text, replica):
    """Inject ``replica="name"`` into every sample line of one
    replica's Prometheus exposition; HELP/TYPE comments pass through
    (the caller de-duplicates them across replicas — Prometheus rejects
    a repeated HELP line for one family)."""
    out = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            out.append(ln)
            continue
        name_part, _, value = ln.rpartition(" ")
        if "{" in name_part:
            fam, rest = name_part.split("{", 1)
            out.append(f'{fam}{{replica="{replica}",{rest} {value}')
        else:
            out.append(f'{name_part}{{replica="{replica}"}} {value}')
    return out
