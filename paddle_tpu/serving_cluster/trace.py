"""Cluster-merged Perfetto export: ONE Chrome trace for a whole
serving cluster.

``export_cluster_trace(gateway_or_router, path)`` merges three event
sources into one ``profiler.ChromeTrace`` document:

  * pid 0 — the GATEWAY process: one complete event per handled HTTP
    request (the gateway's bounded ``http_log``) on an "http" track,
    and one instant per router placement decision (the audit ring) on
    a "router" track — policy, reason, per-candidate scores, attempt;
  * pid 1..N — one process per REPLICA (dead ones included: a killed
    LocalReplica's rings are the post-mortem): its engine's dispatch
    timeline on tid 0 and per-slot request spans on tids 1..B, each
    span carrying its ``trace_id``/``attempt`` args — the same layout
    as ``telemetry.export_chrome_tracing`` for one engine.

Cross-process alignment follows the flight recorder's discipline:
every source contributes a ``(t_wall, t_mono)`` anchor pair captured
at dump time, each monotonic timestamp is rebased to wall time through
its OWN source's anchor, and the whole trace is shifted to the
earliest rebased event — so a gateway HTTP span, the router decision
that placed it, and both replicas' engine spans (attempt 1 on the
killed replica, attempt 2 on the failover target) line up on one
timeline under one trace id.

The output passes ``telemetry.validate_chrome_trace`` (benches and
tests gate on it: ``bench_serving.py --cluster`` fails on an invalid
merged trace, the same discipline as the single-engine export gate).
"""
from __future__ import annotations

import time

from ..inference import telemetry
from .replica import ReplicaError

__all__ = ["export_cluster_trace"]


def _source_anchors(router):
    """(t_wall - t_mono) offsets for the gateway/router's own clocks,
    captured NOW (their events are still in-process — unlike replica
    dumps there is no serialized anchor to read). The gateway's HTTP
    spans stamp ``time.monotonic()``, so its anchor is the plain
    monotonic offset."""
    now_wall = time.time()
    return {"http": now_wall - time.monotonic(),
            "router": now_wall - router.clock()}


def export_cluster_trace(source, path):
    """Write the merged cluster trace; ``source`` is a ``Gateway`` (the
    full picture: http + router + replicas) or a bare ``Router``
    (bench/virtual-clock drives: router + replicas, no http track).
    Unreachable rpc replicas are skipped with a metadata note instead
    of failing the export — a post-mortem tool must degrade, not die.
    Returns ``path``."""
    from ..profiler import ChromeTrace
    gateway = source if hasattr(source, "router") else None
    router = gateway.router if gateway is not None else source

    anchors = _source_anchors(router)
    http_events = []
    http_log_lost = False
    if gateway is not None:
        for i in range(3):
            try:
                http_events = list(gateway.http_log)
                break
            except RuntimeError:
                # the event loop appended mid-iteration (deques guard
                # their iterators); a live gateway is a supported
                # export target, so retry rather than die — and if
                # every retry loses the race, say so in the trace
                # instead of silently exporting an empty HTTP track
                http_log_lost = i == 2
    with router._lock:
        audit = list(router.audit)
    dumps = {}
    unreachable = []
    for name in sorted(router.replicas):
        try:
            dumps[name] = router.replicas[name].trace_dump()
        except ReplicaError:
            unreachable.append(name)

    # ---- rebase: every event to wall time through ITS source's anchor
    times = []
    for ev in http_events:
        times.append(anchors["http"] + ev["t"])
    for ev in audit:
        times.append(anchors["router"] + ev["t"])
    for d in dumps.values():
        a = d["t_wall"] - d["t_mono"]
        for sp in d["spans"]:
            times.extend(a + t for _, t in sp["events"])
        times.extend(a + ev["t"] for ev in d["steps"])
    base = min(times) if times else 0.0

    def us(anchor_off, t):
        return max((anchor_off + t - base) * 1e6, 0.0)

    tr = ChromeTrace()
    tr.process(0, "gateway")
    tr.thread(0, 0, "http")
    tr.thread(0, 1, "router decisions")
    for ev in http_events:
        args = {"trace_id": ev["trace_id"], "status": ev["status"]}
        if ev.get("gid"):
            args["gid"] = ev["gid"]
        tr.complete(f"{ev['method']} {ev['path']} [{ev['status']}]",
                    0, 0, us(anchors["http"], ev["t"]),
                    max(ev["dur_s"] or 0.0, 0.0) * 1e6, args=args)
    for ev in audit:
        tr.instant(f"route[{ev['reason']}] {ev['gid']} -> "
                   f"{ev['chosen']}", 0, 1,
                   us(anchors["router"], ev["t"]))
        # instants carry no args in the shared event model — follow
        # with a zero-duration complete event holding the decision
        # payload (policy, scores, trace context) for inspection
        tr.complete(f"decision {ev['gid']}", 0, 1,
                    us(anchors["router"], ev["t"]), 0.0,
                    args={"trace_id": ev["trace_id"],
                          "policy": ev["policy"],
                          "reason": ev["reason"],
                          "chosen": ev["chosen"],
                          "attempt": ev["attempt"],
                          "scores": ev["scores"]})
    if http_log_lost:
        tr.instant("gateway http log unavailable (snapshot raced the "
                   "event loop 3x — HTTP track incomplete)", 0, 0, 0.0)
    for name in unreachable:
        tr.instant(f"replica {name}: trace unavailable (unreachable)",
                   0, 1, 0.0)

    for pid, name in enumerate(sorted(dumps), start=1):
        d = dumps[name]
        a = d["t_wall"] - d["t_mono"]
        # the per-replica layout is telemetry's single-engine renderer
        # verbatim — shared so the two exports cannot drift apart
        telemetry.render_trace_dump(
            tr, pid, d, lambda t, a=a: us(a, t),
            process_name=f"replica {name}")
    tr.write(path)
    return path
