"""python -m paddle_tpu.serving_cluster — a self-contained demo
cluster: N replicas (each its own ServingEngine + prefix cache over a
shared toy model) behind the gateway, ready for curl.

    JAX_PLATFORMS=cpu python -m paddle_tpu.serving_cluster \
        --replicas 2 --port 8100
    curl -s localhost:8100/v1/models
    curl -s localhost:8100/v1/completions -d \
        '{"prompt": [5, 9, 2, 41], "max_tokens": 8}'
    curl -sN localhost:8100/v1/completions -d \
        '{"prompt": [5, 9, 2, 41], "max_tokens": 8, "stream": true}'
    curl -s localhost:8100/metrics | head

``--workers N`` promotes the replicas OUT OF PROCESS: the gateway
process becomes a supervisor that spawns N worker processes as a gang
(workerlog capture, SIGTERM->grace->SIGKILL teardown — the same
discipline as distributed.launch), rendezvouses them over
``distributed.rpc``, and fronts each with an ``RpcReplica``. Each
worker builds its own engine and calls ``serve_engine`` — the
production recipe (one engine per accelerator process) instead of the
manual ``init_rpc`` glue. A dead worker tears the whole demo down
with a failure report naming the rank and its log tail.

``--mesh-mp M`` makes every engine tensor-parallel over an M-way mesh
(``parallel.init_serving_mesh``): the paged KV pool shards by head AND
the stacked qkv/proj/FFN weights (plus the LM head) shard over 'mp',
so each device holds ~1/M of both the pool and the weight bytes
(``PADDLE_SERVING_MESH_WEIGHTS=0`` opts the weight half out). Workers
inherit the degree via ``PADDLE_SERVING_MESH_MP``; the bring-up
validates the model's head/FFN axes against M up front. On CPU hosts
the mesh devices are forced via XLA_FLAGS automatically.

Flags default from the env contract (``PADDLE_GATEWAY_PORT``,
``PADDLE_GATEWAY_REPLICAS``, ``PADDLE_ROUTER_POLICY``,
``PADDLE_SERVING_MESH_MP``). This is the demo/e2e harness; a real
deployment builds its own engines (one per accelerator) and passes
them to ``LocalReplica``/``serve_engine``.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


# the demo cluster's shared toy-model dims — module-level so the mesh
# bring-up can validate the tensor-parallel layout (H % mp, FF % mp)
# BEFORE any engine build
MODEL_DIMS = {"E": 64, "H": 4, "FF": 128, "L": 2, "V": 256}


def _build_engine(seed, slots, smax, prefix_blocks, cap, role="mixed"):
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.nn.layer.common import Embedding, Linear

    E, H, FF, L, V = (MODEL_DIMS[k] for k in ("E", "H", "FF", "L", "V"))
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    kw = dict(num_slots=slots, max_seq_len=smax, prefill_cap=cap,
              prefix_cache_blocks=prefix_blocks, role=role)
    if role == "prefill":
        # prompt-crunching shape: few slots, one wide flat token
        # budget — the whole batch is prefill chunks, decode never
        # competes for the budget on this engine
        kw.update(num_slots=max(2, slots // 2), flat_budget=True,
                  token_budget=4 * cap, decode_chunk=1)
    elif role == "decode":
        # token-pump shape: deep slot count, small per-step budget —
        # many resident sessions, short steps, low inter-token jitter
        kw.update(num_slots=2 * slots, token_budget=2 * slots)
    return ServingEngine(fmt, embed, head, **kw)


def _parse_roles(spec):
    """'prefill:1,decode:2' -> ["prefill", "decode", "decode"]. The
    pool must be able to both place prompts and decode them: at least
    one prefill-capable AND one decode-capable entry."""
    roles = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, cnt = part.partition(":")
        name = name.strip()
        if name not in ("prefill", "decode", "mixed"):
            raise SystemExit(
                f"--roles: unknown role {name!r} (want prefill, "
                "decode, or mixed)")
        try:
            n = int(cnt)
        except ValueError:
            raise SystemExit(f"--roles: bad count in {part!r}")
        if n < 1:
            raise SystemExit(f"--roles: count must be >= 1 in {part!r}")
        roles.extend([name] * n)
    if not any(r in ("prefill", "mixed") for r in roles):
        raise SystemExit("--roles: no prefill-capable replica — "
                         "prompts would have nowhere to land")
    if not any(r in ("decode", "mixed") for r in roles):
        raise SystemExit("--roles: no decode-capable replica — "
                         "prefilled sessions would have nowhere to go")
    return roles


def _worker_main(args):
    """Worker-process entry (the supervisor re-execs this module with
    --worker-rank): join the rpc rendezvous FIRST (registration is
    cheap — the supervisor's 60s window must not pay for engine
    compiles), then build the engine and serve it."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.parallel import init_serving_mesh

    from .replica import serve_engine

    rank = args.worker_rank
    world = args.workers + 1
    last = None
    for _ in range(200):      # the supervisor's store server races us up
        try:
            rpc.init_rpc(f"cluster_worker{rank}", rank=rank,
                         world_size=world)
            break
        except (OSError, ConnectionError) as e:
            last = e
            time.sleep(0.1)
    else:
        raise RuntimeError(
            f"worker {rank}: rpc rendezvous never came up: {last!r}")
    # PADDLE_SERVING_MESH_MP; unset = no mesh. The model dims validate
    # the full tensor-parallel layout (KV heads + FFN columns) at
    # bring-up — a role worker must fail HERE, not mid-serve
    init_serving_mesh(num_heads=MODEL_DIMS["H"],
                      ffn_dim=MODEL_DIMS["FF"])
    eng = _build_engine(0, args.slots, args.max_seq_len,
                        args.prefix_blocks, args.prefill_cap,
                        role=args.role)
    serve_engine(eng, name=f"replica{rank}", threaded=True)
    print(f"serving_cluster: worker {rank} serving", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        rpc.shutdown()
    return 0


def _spawn_workers(args, master, role_list=None):
    """Spawn the worker gang with workerlog capture; a mid-loop spawn
    failure reaps the already-started ranks (launch discipline).
    ``role_list`` (from --roles) assigns rank r its role by position —
    the worker builds its engine with the matching per-role shape."""
    import subprocess

    from paddle_tpu.distributed.launch.__main__ import _reap_gang

    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []
    try:
        for rank in range(1, args.workers + 1):
            env = dict(os.environ)
            env["PADDLE_MASTER"] = master
            if args.mesh_mp > 1:
                env["PADDLE_SERVING_MESH_MP"] = str(args.mesh_mp)
            role = (role_list[rank - 1] if role_list is not None
                    else "mixed")
            logf = open(os.path.join(
                args.log_dir, f"workerlog.serving.{rank}"), "a")
            logs.append(logf)
            p = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.serving_cluster",
                 "--worker-rank", str(rank),
                 "--workers", str(args.workers),
                 "--slots", str(args.slots),
                 "--max-seq-len", str(args.max_seq_len),
                 "--prefill-cap", str(args.prefill_cap),
                 "--prefix-blocks", str(args.prefix_blocks),
                 "--role", role],
                env=env, stdout=logf, stderr=subprocess.STDOUT)
            p._pd_rank = rank
            procs.append(p)
    except Exception:
        _reap_gang(procs, 5.0)
        for f in logs:
            f.close()
        raise
    return procs, logs


def _wait_ready(replicas, timeout_s=120.0):
    """Block until every worker has installed its engine: registration
    happens before the (slow) engine build, so the first snapshot may
    find no served engine yet — that RuntimeError is 'not ready', any
    transport error is a dead worker."""
    from .replica import ReplicaError

    deadline = time.time() + timeout_s
    for rep in replicas:
        while True:
            try:
                rep.snapshot()
                break
            except ReplicaError:
                raise
            except Exception:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"worker {rep.name!r} never became ready")
                time.sleep(0.25)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving_cluster",
        description="demo cluster: N replicas behind the gateway")
    ap.add_argument("--replicas", type=int, default=int(os.environ.get(
        "PADDLE_GATEWAY_REPLICAS", "2")))
    ap.add_argument("--port", type=int, default=int(os.environ.get(
        "PADDLE_GATEWAY_PORT", "8100")))
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=256)
    ap.add_argument("--prefill-cap", type=int, default=64)
    ap.add_argument("--prefix-blocks", type=int, default=64)
    ap.add_argument("--policy", default=None,
                    help="router policy (default: PADDLE_ROUTER_POLICY "
                         "or prefix_affinity)")
    ap.add_argument("--workers", type=int, default=0,
                    help="spawn N out-of-process rpc workers instead of "
                         "in-process replicas (supervised gang)")
    ap.add_argument("--mesh-mp", type=int, default=int(os.environ.get(
        "PADDLE_SERVING_MESH_MP", "0") or 0),
        help="tensor-parallel engines over an mp-way mesh: the paged "
             "KV pool shards by head and the qkv/proj/FFN weight "
             "stacks by head/column (0/1 = no mesh)")
    ap.add_argument("--log-dir", default="log",
                    help="worker gang log directory (workerlog.serving.N)")
    ap.add_argument("--roles", default=os.environ.get(
        "PADDLE_GATEWAY_ROLES", ""),
        help="disaggregated pool spec 'prefill:1,decode:2' — builds "
             "role-specialized replicas (prefill: flat-budget wide; "
             "decode: deep slots) instead of --replicas mixed ones; "
             "with --workers the spec also sets the worker count")
    ap.add_argument("--worker-rank", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--role", default="mixed",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    role_list = _parse_roles(args.roles) if args.roles else None

    # the mesh needs devices before the first jax import (CPU hosts:
    # forced host devices — same lever as bench_serving --mesh)
    if args.mesh_mp > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{max(8, args.mesh_mp)}").strip()

    if args.worker_rank:
        return _worker_main(args)

    from .gateway import Gateway
    from .router import Router

    procs, logs = [], []
    if args.workers > 0:
        from paddle_tpu.distributed import rpc
        from paddle_tpu.distributed.launch.__main__ import (_free_port,
                                                            _reap_gang,
                                                            _tail)

        from .replica import RpcReplica

        if role_list is not None:
            args.workers = len(role_list)
        master = f"127.0.0.1:{_free_port()}"
        procs, logs = _spawn_workers(args, master, role_list)
        # rank 0 hosts the store; init blocks until the gang registers
        rpc.init_rpc("cluster_gateway", rank=0,
                     world_size=args.workers + 1, master_endpoint=master)
        replicas = [RpcReplica(f"cluster_worker{r}")
                    for r in range(1, args.workers + 1)]
        _wait_ready(replicas)
        n_label = (f"{args.workers} worker processes ({args.roles})"
                   if role_list else f"{args.workers} worker processes")
    else:
        from paddle_tpu.parallel import init_serving_mesh

        from .replica import LocalReplica
        if args.mesh_mp > 1:
            init_serving_mesh(args.mesh_mp,
                              num_heads=MODEL_DIMS["H"],
                              ffn_dim=MODEL_DIMS["FF"])
        # every replica serves the SAME weights (seed-shared toy model)
        # so routing is invisible to outputs — the production contract
        roles = role_list or ["mixed"] * args.replicas
        replicas = [
            LocalReplica(f"{role}{i}" if role_list else f"replica{i}",
                         _build_engine(0, args.slots, args.max_seq_len,
                                       args.prefix_blocks,
                                       args.prefill_cap, role=role))
            for i, role in enumerate(roles)]
        n_label = (f"{len(roles)} replicas ({args.roles})"
                   if role_list else f"{args.replicas} replicas")

    router = Router(replicas, policy=args.policy)
    gw = Gateway(router, port=args.port).start_background()
    mesh_note = (f", mesh mp={args.mesh_mp}" if args.mesh_mp > 1 else "")
    print(f"serving_cluster: {n_label} on "
          f"http://127.0.0.1:{gw.port} (policy {router.policy}"
          f"{mesh_note}) — Ctrl-C to stop", flush=True)
    rc = 0
    try:
        while True:
            time.sleep(1)
            # gang supervision: the first dead worker tears down the
            # demo with a report naming the rank and its log tail
            dead = [p for p in procs if p.poll() is not None]
            if dead:
                p = dead[0]
                path = os.path.join(args.log_dir,
                                    f"workerlog.serving.{p._pd_rank}")
                print(f"serving_cluster: worker {p._pd_rank} died "
                      f"(exit {p.poll()}):\n{_tail(path)}",
                      file=sys.stderr, flush=True)
                rc = 1
                break
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()
        for r in replicas:
            try:
                r.close()
            except Exception:
                pass
        if procs:
            _reap_gang(procs, 5.0)
            for f in logs:
                f.close()
            rpc.shutdown()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
