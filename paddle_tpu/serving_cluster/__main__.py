"""python -m paddle_tpu.serving_cluster — a self-contained demo
cluster: N in-process replicas (each its own ServingEngine + prefix
cache over a shared toy model) behind the gateway, ready for curl.

    JAX_PLATFORMS=cpu python -m paddle_tpu.serving_cluster \
        --replicas 2 --port 8100
    curl -s localhost:8100/v1/models
    curl -s localhost:8100/v1/completions -d \
        '{"prompt": [5, 9, 2, 41], "max_tokens": 8}'
    curl -sN localhost:8100/v1/completions -d \
        '{"prompt": [5, 9, 2, 41], "max_tokens": 8, "stream": true}'
    curl -s localhost:8100/metrics | head

Flags default from the env contract (``PADDLE_GATEWAY_PORT``,
``PADDLE_GATEWAY_REPLICAS``, ``PADDLE_ROUTER_POLICY``). This is the
demo/e2e harness; a real deployment builds its own engines (one per
accelerator) and passes them to ``LocalReplica``/``serve_engine``.
"""
from __future__ import annotations

import argparse
import os
import time


def _build_engine(seed, slots, smax, prefix_blocks, cap):
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.nn.layer.common import Embedding, Linear

    E, H, FF, L, V = 64, 4, 128, 2, 256
    paddle.seed(seed)
    embed = Embedding(V, E)
    fmt = FusedMultiTransformer(E, H, FF, num_layers=L,
                                normalize_before=True)
    head = Linear(E, V, bias_attr=False)
    fmt.eval()
    return ServingEngine(fmt, embed, head, num_slots=slots,
                         max_seq_len=smax, prefill_cap=cap,
                         prefix_cache_blocks=prefix_blocks)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving_cluster",
        description="demo cluster: N local replicas behind the gateway")
    ap.add_argument("--replicas", type=int, default=int(os.environ.get(
        "PADDLE_GATEWAY_REPLICAS", "2")))
    ap.add_argument("--port", type=int, default=int(os.environ.get(
        "PADDLE_GATEWAY_PORT", "8100")))
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=256)
    ap.add_argument("--prefill-cap", type=int, default=64)
    ap.add_argument("--prefix-blocks", type=int, default=64)
    ap.add_argument("--policy", default=None,
                    help="router policy (default: PADDLE_ROUTER_POLICY "
                         "or prefix_affinity)")
    args = ap.parse_args(argv)

    from .gateway import Gateway
    from .replica import LocalReplica
    from .router import Router

    # every replica serves the SAME weights (seed-shared toy model) so
    # routing is invisible to outputs — exactly the production contract
    replicas = [
        LocalReplica(f"replica{i}",
                     _build_engine(0, args.slots, args.max_seq_len,
                                   args.prefix_blocks, args.prefill_cap))
        for i in range(args.replicas)]
    router = Router(replicas, policy=args.policy)
    gw = Gateway(router, port=args.port).start_background()
    print(f"serving_cluster: {args.replicas} replicas on "
          f"http://127.0.0.1:{gw.port} (policy {router.policy}) — "
          "Ctrl-C to stop", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()
        for r in replicas:
            r.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
