"""Replica handles: ONE interface over an in-process engine thread and
a remote engine behind ``paddle_tpu.distributed.rpc``.

The router never sees a ServingEngine — it sees a *replica*: submit /
poll / harvest / release / snapshot / heartbeat. Two implementations:

  * ``LocalReplica`` — thread-per-engine. The driver thread loops
    ``engine.step()`` while there is work and publishes a heartbeat
    each iteration; every engine touch (submit, harvest, snapshot)
    serializes on one lock, so the single-threaded engine stays
    single-threaded. ``threaded=False`` hands the drive loop to the
    caller (``pump()``) — the bench and the router unit tests use it to
    run the whole cluster on a virtual clock, deterministically.
  * ``RpcReplica`` — the same interface over ``rpc_sync`` to a worker
    process that runs ``serve_engine()`` (which wraps ITS engine in a
    LocalReplica — the locking story is identical in and out of
    process). Heartbeats are ``rpc.ping`` with a SHORT timeout, so a
    dead worker is detected at heartbeat cadence, not at the 30s rpc
    default inside a user-facing call.

Death is a first-class state: ``kill()`` (tests/bench) freezes the
driver without draining — heartbeats stop, ``alive`` flips false, and
every engine touch raises ``ReplicaError`` so the router's failover
path (drain + re-submit elsewhere) is the ONLY way forward, exactly
like a crashed process.
"""
from __future__ import annotations

import os
import threading
import time

from ..inference.serving import AdmissionFull

__all__ = ["ReplicaError", "LocalReplica", "RpcReplica", "serve_engine"]


class ReplicaError(RuntimeError):
    """The replica is dead or unreachable — the router must fail over.
    Deliberately DISTINCT from AdmissionFull: backpressure means retry
    or spill, death means drain and re-route."""


_RTT_ALPHA = 0.2    # EWMA smoothing for proxy-side RTT observation


class _HealthMeter:
    """Proxy-side gray-failure observables, shared by both replica
    flavors: an EWMA of per-operation round-trip time (submit / harvest
    / snapshot / ping) and a consecutive-transport-error streak. The
    router's health scorer reads these through ``health_stats()`` —
    RTTs catch a slow-but-alive replica, the error streak catches a
    lossy link, and NEITHER declares death (that stays the heartbeat
    sweep's job)."""

    __slots__ = ("rtt", "consec_errors", "errors_total", "ops_total")

    def __init__(self):
        self.rtt = {}             # op -> EWMA seconds
        self.consec_errors = 0
        self.errors_total = 0
        self.ops_total = 0

    def ok(self, op, dt):
        prev = self.rtt.get(op)
        self.rtt[op] = dt if prev is None else (
            (1.0 - _RTT_ALPHA) * prev + _RTT_ALPHA * dt)
        self.consec_errors = 0
        self.ops_total += 1

    def err(self):
        self.consec_errors += 1
        self.errors_total += 1
        self.ops_total += 1

    def stats(self):
        return {
            "rtt_ewma_s": dict(self.rtt),
            "consecutive_errors": self.consec_errors,
            "errors_total": self.errors_total,
            "ops_total": self.ops_total,
        }


class LocalReplica:
    """Thread-per-engine in-process replica (see module docstring)."""

    def __init__(self, name, engine, threaded=True, clock=None,
                 idle_wait_s=0.002, step_hook=None):
        self.name = name
        self.engine = engine
        # pool role for the router's placement filter (prefill workers
        # never take decode-resident sessions and vice versa); engines
        # predating the role knob read as mixed = place anywhere
        self.role = getattr(engine, "role", "mixed")
        self._lock = threading.RLock()
        self._clock = clock or time.monotonic
        self._hb = self._clock()
        self._failed = False
        self._stop = False
        self._wake = threading.Event()
        self._idle_wait_s = float(idle_wait_s)
        # called as hook(self) after every WORKING engine step — the
        # deterministic fault-drill lever (kill at exactly step K,
        # mid-request, regardless of scheduler/socket timing)
        self._step_hook = step_hook
        self._health = _HealthMeter()
        self._thread = None
        if threaded:
            self._thread = threading.Thread(
                target=self._drive, daemon=True,
                name=f"replica-{name}")
            self._thread.start()

    # ------------------------------------------------------------ drive
    def _drive(self):
        while not self._stop:
            if self._failed:
                return                    # crash: heartbeat freezes
            with self._lock:
                work = self.engine.has_work and not self._failed
                if work:
                    self.engine.step()
            if work and self._step_hook is not None:
                self._step_hook(self)
            self._hb = self._clock()
            if not work:
                self._wake.wait(self._idle_wait_s)
                self._wake.clear()

    def pump(self):
        """Unthreaded drive: one engine step if there is work; returns
        tokens emitted. The caller owns the cadence (virtual-clock
        benches, deterministic tests)."""
        self._check_alive()
        with self._lock:
            work = self.engine.has_work
            out = self.engine.step() if work else 0
        if work and self._step_hook is not None:
            self._step_hook(self)
        self._hb = self._clock()
        return out

    # ---------------------------------------------------------- health
    def heartbeat_age(self):
        return self._clock() - self._hb

    @property
    def alive(self):
        if self._failed or self._stop:
            return False
        return self._thread is None or self._thread.is_alive()

    def _check_alive(self):
        if not self.alive:
            raise ReplicaError(f"replica {self.name!r} is dead")

    def health_stats(self):
        """Proxy-side gray-failure observables (see _HealthMeter)."""
        return self._health.stats()

    def kill(self):
        """Simulated crash (tests/bench/fault drills): the driver stops
        mid-flight WITHOUT draining — in-flight requests are stranded
        exactly as a SIGKILLed process would strand them."""
        self._failed = True
        self._wake.set()

    def close(self):
        """Graceful stop (not a crash): the drive thread exits; the
        engine keeps its state."""
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # ----------------------------------------------------------- engine
    def submit(self, prompt, **kw):
        """submit + track under ONE lock hold: the streaming cursor is
        registered before the driver thread can possibly finish the
        request, closing the results-cap race by construction."""
        self._check_alive()
        t0 = self._clock()
        with self._lock:
            rid = self.engine.submit(prompt, **kw)
            self.engine.track(rid)
        self._health.ok("submit", self._clock() - t0)
        self._wake.set()
        return rid

    def harvest(self, rid):
        self._check_alive()
        t0 = self._clock()
        with self._lock:
            out = self.engine.harvest_new_tokens(rid)
        self._health.ok("harvest", self._clock() - t0)
        return out

    def poll(self, rid):
        self._check_alive()
        with self._lock:
            return self.engine.poll(rid)

    def release(self, rid):
        if not self.alive:
            return                        # nothing to free on a corpse
        with self._lock:
            self.engine.release(rid)

    def export_slot(self, rid, skip_blocks=0):
        """Live-migration export: detach one request's full decode
        state (engine.export_slot) under the replica lock, so the
        driver thread can never interleave a step mid-export.
        ``skip_blocks`` elides KV blocks the target already staged
        (streamed handoff)."""
        self._check_alive()
        with self._lock:
            return self.engine.export_slot(rid, skip_blocks=skip_blocks)

    def import_slot(self, state, staged=None):
        """Live-migration import: resume an exported session here.
        Tracks the new rid under the SAME lock hold, exactly like
        submit — the streaming cursor exists before the driver can
        finish the request. ``staged`` names a stage_kv_blocks tag
        whose blocks splice in as the session's leading KV."""
        self._check_alive()
        with self._lock:
            rid = self.engine.import_slot(state, staged=staged)
            self.engine.track(rid)
        self._wake.set()
        return rid

    def export_kv_prefix(self, rid, start_block=0, min_blocks=1):
        """Streamed-handoff source: read a live request's committed
        full KV blocks from ``start_block`` on, WITHOUT detaching it
        (engine.export_kv_prefix). Returns (blocks, cursor)."""
        self._check_alive()
        with self._lock:
            return self.engine.export_kv_prefix(
                rid, start_block=start_block, min_blocks=min_blocks)

    def stage_kv_blocks(self, tag, blocks):
        """Streamed-handoff sink: land KV blocks ahead of their
        session's import under ``tag`` (engine.stage_kv_blocks;
        AdmissionFull = backpressure, the stream stays put)."""
        self._check_alive()
        with self._lock:
            return self.engine.stage_kv_blocks(tag, blocks)

    def abort_stage(self, tag):
        """Release a staging tag's blocks (handoff fell through)."""
        if not self.alive:
            return 0                      # nothing to free on a corpse
        with self._lock:
            return self.engine.abort_stage(tag)

    def snapshot(self):
        self._check_alive()
        t0 = self._clock()
        with self._lock:
            snap = self.engine.telemetry_snapshot()
        self._health.ok("snapshot", self._clock() - t0)
        snap["replica"] = self.name
        return snap

    def metrics_prometheus(self):
        self._check_alive()
        with self._lock:
            return self.engine.metrics_prometheus()

    def trace_dump(self):
        """JSON-able dump of the engine's telemetry rings for the
        cluster trace export (telemetry.trace_dump + replica name).
        Deliberately NO alive check: a killed replica's rings are the
        post-mortem — its stranded in-flight spans are exactly what the
        merged kill-drill trace must show."""
        from ..inference.telemetry import trace_dump
        with self._lock:
            d = trace_dump(self.engine)
        d["replica"] = self.name
        return d


# ----------------------------------------------------------- rpc worker
# Module-level state + functions so they pickle by reference through
# rpc (a bound method would drag the whole replica object along).
_WORKER: list = [None]


def serve_engine(engine, name="replica", threaded=True):
    """Install ``engine`` as THIS process's served replica (wrapped in a
    LocalReplica — one lock story everywhere) and return the wrapper.
    Call after ``rpc.init_rpc``; the gateway process then drives it via
    ``RpcReplica(worker_name)``. ``threaded=False`` leaves the drive
    loop to the caller's ``pump()`` (deterministic tests)."""
    _WORKER[0] = LocalReplica(name, engine, threaded=threaded)
    return _WORKER[0]


def _served():
    rep = _WORKER[0]
    if rep is None:
        raise RuntimeError("this worker serves no engine — call "
                           "serving_cluster.replica.serve_engine first")
    return rep


def _rw_submit(prompt, kw):
    return _served().submit(prompt, **kw)


def _rw_harvest(rid):
    return _served().harvest(rid)


def _rw_poll(rid):
    return _served().poll(rid)


def _rw_release(rid):
    return _served().release(rid)


def _rw_export_slot(rid, skip_blocks=0):
    return _served().export_slot(rid, skip_blocks=skip_blocks)


def _rw_import_slot(state, staged=None):
    return _served().import_slot(state, staged=staged)


def _rw_export_kv_prefix(rid, start_block, min_blocks=1):
    return _served().export_kv_prefix(rid, start_block=start_block,
                                      min_blocks=min_blocks)


def _rw_stage_kv_blocks(tag, blocks):
    return _served().stage_kv_blocks(tag, blocks)


def _rw_abort_stage(tag):
    return _served().abort_stage(tag)


def _rw_role():
    return _served().role


def _rw_snapshot():
    return _served().snapshot()


def _rw_prometheus():
    return _served().metrics_prometheus()


def _rw_trace_dump():
    return _served().trace_dump()


class RpcReplica:
    """The replica interface over ``distributed/rpc.py``: every engine
    touch is one ``rpc_sync`` to ``worker_name``; transport failures
    (dead worker, timeout) surface as ``ReplicaError`` so the router
    treats an unreachable process exactly like a dead thread.
    ``AdmissionFull`` pickles through the rpc exception channel intact
    — backpressure stays backpressure across the process boundary."""

    def __init__(self, worker_name, timeout=None, ping_timeout=None):
        from ..distributed import rpc
        self._rpc = rpc
        self.name = worker_name
        self.engine = None                # remote — no local handle
        self._timeout = float(
            timeout if timeout is not None
            else os.environ.get("PADDLE_RPC_TIMEOUT_S", "30"))
        # liveness-probe deadline, tunable independently of the call
        # deadline: arg -> PADDLE_RPC_PING_TIMEOUT_S -> the gateway
        # heartbeat-probe default (a 30s probe would hold every health
        # sweep hostage on one wedged worker)
        self._ping_timeout = float(
            ping_timeout if ping_timeout is not None
            else os.environ.get(
                "PADDLE_RPC_PING_TIMEOUT_S",
                os.environ.get("PADDLE_GATEWAY_HB_TIMEOUT_S", "2")))
        self._health = _HealthMeter()
        self._dead = False
        self._hb = time.monotonic()
        self._role = None                 # fetched lazily, then cached

    @property
    def role(self):
        """The worker's pool role — fetched once over rpc (it is
        engine-construction-time config and cannot change), cached for
        every later placement read."""
        if self._role is None:
            self._role = str(self._call(_rw_role,
                                        timeout=self._ping_timeout))
        return self._role

    def _call(self, fn, *args, timeout=None):
        from ..testing.fault import FaultInjected
        if self._dead:
            raise ReplicaError(f"replica {self.name!r} is dead")
        op = getattr(fn, "__name__", "rpc").replace("_rw_", "")
        t0 = time.monotonic()
        try:
            out = self._rpc.rpc_sync(
                self.name, fn, args=args,
                timeout=self._timeout if timeout is None else timeout)
        except AdmissionFull:
            self._hb = time.monotonic()   # a shed IS a live round-trip
            self._health.ok(op, time.monotonic() - t0)
            raise
        except (TimeoutError, ConnectionError, OSError,
                FaultInjected) as e:
            # FaultInjected is the flaky-transport injection flavor —
            # by contract indistinguishable from a real wire failure
            self._health.err()
            raise ReplicaError(
                f"replica {self.name!r} unreachable: {e!r}") from e
        self._hb = time.monotonic()
        self._health.ok(op, time.monotonic() - t0)
        return out

    # ---------------------------------------------------------- health
    def heartbeat_age(self):
        return time.monotonic() - self._hb

    def health_stats(self):
        """Proxy-side gray-failure observables (see _HealthMeter)."""
        return self._health.stats()

    @property
    def alive(self):
        if self._dead:
            return False
        try:
            rtt = self._rpc.ping(self.name, timeout=self._ping_timeout)
        except Exception:
            self._health.err()
            return False
        self._hb = time.monotonic()
        self._health.ok("ping", rtt)
        return True

    def kill(self):
        """Client-side tombstone (the worker process is killed out of
        band); every later touch raises ReplicaError immediately."""
        self._dead = True

    def close(self):
        self._dead = True

    # ----------------------------------------------------------- engine
    def submit(self, prompt, **kw):
        return self._call(_rw_submit, list(prompt), kw)

    def harvest(self, rid):
        return self._call(_rw_harvest, rid)

    def poll(self, rid):
        return self._call(_rw_poll, rid)

    def release(self, rid):
        try:
            return self._call(_rw_release, rid)
        except ReplicaError:
            return None                   # nothing to free on a corpse

    def export_slot(self, rid, skip_blocks=0):
        """Migration export over rpc: the KV block bytes ride the
        pickle channel (a dead/unreachable worker surfaces as
        ReplicaError — the router's abort-to-failover trigger)."""
        return self._call(_rw_export_slot, rid, skip_blocks)

    def import_slot(self, state, staged=None):
        """Migration import over rpc; AdmissionFull pickles through
        intact (a full target is backpressure, not death — the drain
        tries the next candidate)."""
        return self._call(_rw_import_slot, state, staged)

    def export_kv_prefix(self, rid, start_block=0, min_blocks=1):
        return self._call(_rw_export_kv_prefix, rid, start_block,
                          min_blocks)

    def stage_kv_blocks(self, tag, blocks):
        return self._call(_rw_stage_kv_blocks, tag, blocks)

    def abort_stage(self, tag):
        try:
            return self._call(_rw_abort_stage, tag)
        except ReplicaError:
            return 0                      # nothing to free on a corpse

    def snapshot(self):
        # the routing payload is tiny and polled at heartbeat cadence:
        # a frozen worker must stall a snapshot for the SHORT probe
        # timeout, never the 30s user-facing call default (the router
        # may hold its lock across a submit-path refresh)
        return self._call(_rw_snapshot, timeout=self._ping_timeout)

    def metrics_prometheus(self):
        return self._call(_rw_prometheus)

    def trace_dump(self):
        """The worker's telemetry rings over rpc (ReplicaError when the
        process is gone — unlike a LocalReplica there is no in-process
        corpse to read; the cluster export skips it with a note)."""
        return self._call(_rw_trace_dump)
