"""OpenAI-compatible wire protocol for the cluster gateway.

ONE module owns the HTTP surface: request parsing/validation, the exact
field set of every response shape, the SSE chunk format, and the
error-code mapping. ``tools/check_http_surface.py`` asserts a LIVE
gateway's responses against these constants (standalone and as a tier-1
test), so the OpenAI-compat surface cannot drift silently — the same
discipline ``PROMETHEUS_NAMES`` applies to the metrics surface.

Honesty notes (documented, not hidden):

  * The repo has no tokenizer, so ``prompt`` is a list of int token ids
    and each choice carries a ``tokens`` extension field; ``text`` is
    the space-joined decimal ids (curl-able, diffable, honest).
  * Sampling mode is ENGINE config (baked into the one compiled step —
    see serving.py), so per-request ``temperature``/``top_p`` are
    accepted and IGNORED like other unknown fields; per-request knobs
    that ARE data (``max_tokens``, ``stop_token_id``, ``min_tokens``,
    ``repetition_penalty``, ``deadline_s``) pass through.
  * ``request_id`` is the idempotency key: re-submitting the same id
    while the original is live returns the same routed request instead
    of running it twice — the failover path leans on this. The window
    is the assignment's lifetime (the router forgets delivered
    requests), not forever.
"""
from __future__ import annotations

import json

# import-light by design (telemetry pulls no jax/numpy at module load):
# the QoS class vocabulary is shared engine <-> wire surface
from ..inference.telemetry import QOS_CLASSES, QOS_DEFAULT

__all__ = ["ProtocolError", "CompletionRequest", "ERROR_STATUS",
           "RETRY_AFTER_S", "RETRY_AFTER_MAX_S", "COMPLETION_FIELDS",
           "CHOICE_FIELDS", "USAGE_FIELDS", "STREAM_CHUNK_FIELDS",
           "MODELS_FIELDS", "MODEL_ENTRY_FIELDS", "HEALTHZ_FIELDS",
           "HEALTHZ_REPLICA_FIELDS",
           "SCALE_FIELDS", "DRAIN_FIELDS", "ERROR_BODY_FIELDS",
           "ERROR_BODY_FIELDS_429", "REASON_FOR_429",
           "PRIORITY_HEADER", "TENANT_HEADER",
           "ENDPOINTS", "TRACE_HEADER", "parse_completion_request",
           "completion_response", "stream_chunk", "sse_event",
           "SSE_DONE", "error_body", "finish_reason"]


# ------------------------------------------------------------ error map
# exception/condition -> (HTTP status, OpenAI-style error type). The
# gateway maps engine exceptions through exactly this table; the
# surface check pins every row end-to-end over real HTTP.
ERROR_STATUS = {
    "admission_full": 429,      # ServingEngine.AdmissionFull: shed
    "rate_limited": 429,        # tenant token bucket empty
    "quota_exceeded": 429,      # tenant live-request quota hit
    "deadline_exceeded": 504,   # deadline_s lapsed before completion
    "unknown_model": 404,       # model id not served here
    "not_found": 404,           # unknown route / unknown request id
    "bad_request": 400,         # malformed JSON / invalid fields
    "no_replica": 503,          # every replica dead/unreachable
    "conflict": 409,            # admin op refused in the current state
                                # (no autoscaler; draining the last
                                # alive replica)
    "internal": 500,            # anything else (bug, not backpressure)
}

# 429 responses carry Retry-After (seconds) — honest backpressure tells
# the client WHEN, not just no. The value is COMPUTED from the measured
# queue drain rate (Router.retry_after_s: total queued / finished-per-
# second over the recent snapshot window), floored here and capped at
# RETRY_AFTER_MAX_S so a stalled window can't tell clients to wait an
# hour (or to hammer a saturated cluster every second).
RETRY_AFTER_S = 1
RETRY_AFTER_MAX_S = 30

# QoS headers: X-Priority selects the request's class (the JSON body's
# "priority" field wins when both are present — the body is the signed
# payload, the header the proxy-injectable convenience) and X-Tenant
# keys the gateway's per-tenant token bucket + quota. Both optional:
# absent priority = "normal", absent tenant = the shared anonymous
# bucket-less pool.
PRIORITY_HEADER = "X-Priority"
TENANT_HEADER = "X-Tenant"

# the end-to-end trace context header: the gateway honors an inbound
# id (so an upstream proxy can pre-mint) or mints one, echoes it on
# EVERY response, and threads it through router -> replica -> engine —
# one curl -H "X-Request-Id: ..." is findable in the merged cluster
# trace, the router audit, and each replica's request spans
TRACE_HEADER = "X-Request-Id"

# ---------------------------------------------------- response shapes
COMPLETION_FIELDS = ("id", "object", "created", "model", "choices",
                     "usage", "trace_id")
CHOICE_FIELDS = ("index", "text", "tokens", "finish_reason")
USAGE_FIELDS = ("prompt_tokens", "completion_tokens", "total_tokens")
STREAM_CHUNK_FIELDS = ("id", "object", "created", "model", "choices",
                       "trace_id")
MODELS_FIELDS = ("object", "data")
MODEL_ENTRY_FIELDS = ("id", "object", "owned_by")
HEALTHZ_FIELDS = ("status", "replicas_alive", "replicas_total",
                  "replicas")
# per-replica gray-failure probe entry under /healthz "replicas"
HEALTHZ_REPLICA_FIELDS = ("verdict", "breaker", "signal_s")
# the elastic admin surface: scale status (GET and the POST /admin/scale
# response) and the drain summary. Autoscaler-less gateways report the
# same field set with null bounds — the shape never varies.
SCALE_FIELDS = ("replicas_alive", "replicas_total", "draining",
                "migrations_total", "migration_aborts_total",
                "scale_events_up", "scale_events_down", "autoscaler",
                "min_replicas", "max_replicas",
                # disaggregated serving: alive replicas per role
                # ({"prefill": n, "decode": n, "mixed": n}) and the
                # completed prefill->decode KV handoff count
                "roles", "handoffs_total")
# "expired": a deadline_s stream whose remaining budget lapsed during
# the drain itself — terminal, but the operator must see it in the
# drain accounting (migrated+failed_over+orphaned+expired covers every
# live assignment the drain touched)
DRAIN_FIELDS = ("replica", "migrated", "failed_over", "orphaned",
                "expired")
ERROR_BODY_FIELDS = ("message", "type", "code")
# EVERY 429 additionally carries a machine-readable shed cause, so a
# client can distinguish "the cluster is full, back off" (overload)
# from "YOUR tenant hit its limit" (rate_limited / quota_exceeded) —
# the latter two must not trigger fleet-wide client backoff
ERROR_BODY_FIELDS_429 = ("message", "type", "code", "reason")
REASON_FOR_429 = {
    "admission_full": "overload",
    "rate_limited": "rate_limited",
    "quota_exceeded": "quota_exceeded",
}

# route -> top-level response field tuple (None = non-JSON body, e.g.
# the Prometheus text exposition). The surface check walks this table.
ENDPOINTS = {
    "POST /v1/completions": COMPLETION_FIELDS,
    "GET /v1/models": MODELS_FIELDS,
    "GET /healthz": HEALTHZ_FIELDS,
    "GET /metrics": None,
    "GET /admin/scale": SCALE_FIELDS,
    "POST /admin/scale": SCALE_FIELDS,
    "POST /admin/drain": DRAIN_FIELDS,
}

SSE_DONE = b"data: [DONE]\n\n"


class ProtocolError(Exception):
    """A request the protocol rejects: ``code`` indexes ERROR_STATUS."""

    def __init__(self, code, message):
        if code not in ERROR_STATUS:
            raise ValueError(f"unknown protocol error code {code!r}")
        super().__init__(message)
        self.code = code
        self.message = message


class CompletionRequest:
    """Validated POST /v1/completions payload."""

    __slots__ = ("model", "prompt", "max_tokens", "stream",
                 "stop_token_id", "min_tokens", "repetition_penalty",
                 "deadline_s", "request_id", "priority")

    def __init__(self, model, prompt, max_tokens, stream, stop_token_id,
                 min_tokens, repetition_penalty, deadline_s, request_id,
                 priority=QOS_DEFAULT):
        self.model = model
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.stream = stream
        self.stop_token_id = stop_token_id
        self.min_tokens = min_tokens
        self.repetition_penalty = repetition_penalty
        self.deadline_s = deadline_s
        self.request_id = request_id
        self.priority = priority

    def submit_kwargs(self):
        """The ServingEngine.submit keyword view of this request."""
        return dict(max_new_tokens=self.max_tokens,
                    eos_token_id=self.stop_token_id,
                    min_length=self.min_tokens,
                    repetition_penalty=self.repetition_penalty,
                    deadline_s=self.deadline_s,
                    priority=self.priority)


def _int_field(body, key, default, lo=None):
    v = body.get(key, default)
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, int):
        raise ProtocolError("bad_request",
                            f"{key!r} must be an integer, got {v!r}")
    if lo is not None and v < lo:
        raise ProtocolError("bad_request", f"{key!r} must be >= {lo}")
    return v


def parse_completion_request(body, served_model, priority_header=None):
    """Validate a decoded JSON body against the served model; raises
    ProtocolError(bad_request / unknown_model). ``priority_header``
    carries the inbound X-Priority value (the body's "priority" field
    wins when both are present)."""
    if not isinstance(body, dict):
        raise ProtocolError("bad_request", "body must be a JSON object")
    model = body.get("model", served_model)
    if not isinstance(model, str):
        raise ProtocolError("bad_request", "'model' must be a string")
    if model != served_model:
        raise ProtocolError(
            "unknown_model",
            f"model {model!r} is not served here (served: "
            f"{served_model!r})")
    prompt = body.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and t >= 0 for t in prompt)):
        raise ProtocolError(
            "bad_request",
            "'prompt' must be a non-empty list of int token ids (this "
            "stack serves token ids; there is no server-side tokenizer)")
    rp = body.get("repetition_penalty", 1.0)
    if not isinstance(rp, (int, float)) or isinstance(rp, bool) or rp <= 0:
        raise ProtocolError("bad_request",
                            "'repetition_penalty' must be a positive "
                            "number")
    dl = body.get("deadline_s")
    if dl is not None and (isinstance(dl, bool)
                           or not isinstance(dl, (int, float))
                           or dl < 0):
        raise ProtocolError("bad_request",
                            "'deadline_s' must be a non-negative number")
    rid = body.get("request_id")
    if rid is not None and not isinstance(rid, str):
        raise ProtocolError("bad_request", "'request_id' must be a "
                            "string")
    prio = body.get("priority", priority_header)
    if prio is None:
        prio = QOS_DEFAULT
    if prio not in QOS_CLASSES:
        raise ProtocolError(
            "bad_request",
            f"'priority' must be one of {list(QOS_CLASSES)}, got "
            f"{prio!r}")
    # an explicit JSON null means "use the default" (OpenAI semantics),
    # never a None that would reach the engine's integer comparisons
    mt = _int_field(body, "max_tokens", 16, lo=1)
    mn = _int_field(body, "min_tokens", 0, lo=0)
    return CompletionRequest(
        model=model, prompt=[int(t) for t in prompt],
        max_tokens=16 if mt is None else mt,
        stream=bool(body.get("stream", False)),
        stop_token_id=_int_field(body, "stop_token_id", None, lo=0),
        min_tokens=0 if mn is None else mn,
        repetition_penalty=float(rp),
        deadline_s=None if dl is None else float(dl),
        request_id=rid, priority=prio)


def _choice(tokens, reason):
    return {"index": 0, "text": " ".join(str(t) for t in tokens),
            "tokens": list(tokens), "finish_reason": reason}


def completion_response(req_id, model, created, tokens, reason,
                        prompt_tokens, trace_id=None):
    return {
        "id": req_id, "object": "text_completion",
        "created": int(created), "model": model,
        "choices": [_choice(tokens, reason)],
        "usage": {"prompt_tokens": int(prompt_tokens),
                  "completion_tokens": len(tokens),
                  "total_tokens": int(prompt_tokens) + len(tokens)},
        "trace_id": trace_id,
    }


def stream_chunk(req_id, model, created, tokens, reason=None,
                 trace_id=None):
    """One SSE data payload: the DELTA tokens since the last chunk
    (``finish_reason`` only on the final chunk, OpenAI-style; every
    chunk carries the request's trace id so a mid-stream failover is
    correlatable from the client side)."""
    return {"id": req_id, "object": "text_completion.chunk",
            "created": int(created), "model": model,
            "choices": [_choice(tokens, reason)], "trace_id": trace_id}


def sse_event(payload) -> bytes:
    return b"data: " + json.dumps(payload).encode() + b"\n\n"


def error_body(code, message):
    """OpenAI-style error envelope; returns (status, body_dict). 429s
    auto-carry the machine-readable ``reason`` field (REASON_FOR_429)."""
    err = {"message": message, "type": code, "code": code}
    if ERROR_STATUS[code] == 429:
        err["reason"] = REASON_FOR_429[code]
    return ERROR_STATUS[code], {"error": err}


def finish_reason(tokens, stop_token_id, expired):
    """The finish_reason contract: ``timeout`` for deadline expiry,
    ``stop`` when the last token is the request's stop id, ``length``
    otherwise (max_tokens exhausted). A fourth value, ``error``, is
    emitted directly by the gateway when a stream that already sent
    bytes cannot continue (e.g. every replica died mid-request) — the
    stream still terminates with a well-formed chunk + ``[DONE]``
    instead of a second HTTP response spliced into the event stream."""
    if expired:
        return "timeout"
    if stop_token_id is not None and tokens \
            and tokens[-1] == stop_token_id:
        return "stop"
    return "length"
