"""Asyncio HTTP gateway: one OpenAI-compatible endpoint over N replicas.

Stdlib-only (asyncio streams + a minimal HTTP/1.1 parser — the
container pins its dependency set, so no aiohttp): the gateway parses
requests, delegates placement/failover to the Router, and streams
tokens back as JSON or SSE. Engine-touching calls (submit / harvest /
refresh — they take a replica lock, or an rpc round-trip) run in the
default thread-pool executor so one slow replica never stalls the
accept loop.

Endpoints (wire shapes pinned in protocol.py, end-to-end by
tools/check_http_surface.py):

  * ``POST /v1/completions`` — JSON, or SSE when ``"stream": true``
    (one chunk per harvest batch, ``data: [DONE]`` terminator).
  * ``GET /v1/models`` — the single served model id.
  * ``GET /healthz``   — ok/degraded + replica counts (degraded = some
    but not all replicas dead; a fully dead cluster still answers,
    status ``down`` — the load balancer's probe must not hang), plus
    per-replica gray-failure verdicts and circuit-breaker states (a
    replica can be alive yet shed from placement).
  * ``GET /metrics``   — the router's aggregated Prometheus exposition
    (every replica's engine metrics with a ``replica`` label + router
    gauges).
  * ``GET/POST /admin/scale`` — elastic status / manual replica-count
    target (POST needs an Autoscaler: its spawn hook builds replicas);
    ``POST /admin/drain`` — graceful single-replica drain (live
    sessions migrate off, then the replica retires; rolling restarts).
    Admin ops refused in the current state (no autoscaler, draining
    the last replica) map to 409 ``conflict``.

Backpressure is honest end-to-end: AdmissionFull from every replica →
HTTP 429 with ``Retry-After``; ``deadline_s`` expiry → 504; all
replicas dead → 503. A replica dying mid-stream never errors the
stream — the router fails over and the replayed greedy prefix is
skipped (router.py), so the client just sees one slow poll interval.

QoS / multi-tenant admission (all off by default):

  * ``X-Priority`` (or the body's ``"priority"`` field, which wins)
    tags the request ``high``/``normal``/``low`` — threaded through
    the router into the engine's per-class queues, weighted-fair
    packer, and preemption policy (inference/serving.py);
  * ``X-Tenant`` keys a per-tenant TOKEN BUCKET
    (``PADDLE_TENANT_RATE`` req/s refill, ``PADDLE_TENANT_BURST``
    capacity) and a live-request quota (``PADDLE_TENANT_QUOTA``).
    Over-rate → 429 ``reason=rate_limited``; over-quota → 429
    ``reason=quota_exceeded`` — both with ``Retry-After`` computed
    from the TENANT'S OWN bucket refill time (not the cluster drain
    rate), so a throttled tenant backs off by its own allowance while
    everyone else's 429s keep the drain-rate hint;
  * SLO-aware shedding (``PADDLE_QOS_SHED_DEPTH`` > 0): a LOW-class
    arrival is refused 429 ``reason=overload`` when the cluster's
    mean queue depth crosses the watermark AND the PR-11 queue-vs-
    service decomposition attributes the SLO pain to queueing
    (``violated_queue >= violated_service``) — shedding only helps
    when waiting, not service time, is the bottleneck.

Trace plane (the cluster observability spine):

  * every HTTP request gets a TRACE ID — inbound ``X-Request-Id``
    honored, else minted — echoed on the response (header + the
    ``trace_id`` body field + every SSE chunk) and threaded through
    the router into each replica engine's request spans, surviving
    failover re-submits (same id, incremented attempt);
  * every handled request lands one HTTP SPAN (method, path, status,
    duration, trace id, gid) in a bounded ring
    (``PADDLE_GATEWAY_TRACE_RING``, default 2048; 0 disables) — the
    gateway pid's track in ``export_cluster_trace`` (trace.py);
  * per-endpoint+status latency histograms ride the process-global
    ``telemetry.runtime_histogram`` registry
    (``paddle_gateway_http_request_seconds_<endpoint>_<status>``),
    exposed under ``replica="gateway"`` in ``/metrics`` — the
    gateway's own accept/parse/stream time was previously invisible.

Env knobs: ``PADDLE_GATEWAY_PORT`` (8100; 0 = ephemeral),
``PADDLE_GATEWAY_POLL_S`` (harvest poll interval, 0.004),
``PADDLE_GATEWAY_HB_S`` (health sweep interval, 0.25),
``PADDLE_GATEWAY_TRACE_RING`` (HTTP span ring) — plus the router's
``PADDLE_ROUTER_POLICY`` / ``PADDLE_ROUTER_SPILL_DEPTH`` /
``PADDLE_ROUTER_AUDIT_RING`` / ``PADDLE_GATEWAY_HB_DEAD_S`` and the
rpc replica's ``PADDLE_GATEWAY_HB_TIMEOUT_S``. All registered in
``paddle_tpu.testing.GW_ENV_VARS`` (conftest leak guard).
"""
from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import threading
import time
import uuid
from collections import deque

from ..inference.serving import AdmissionFull
from ..inference.telemetry import runtime_histogram
from . import protocol
from .router import NoReplicaError

__all__ = ["Gateway"]

_MAX_BODY = 8 << 20                       # 8 MiB: token-id prompts only

# endpoint key for the per-endpoint+status latency histogram names
# (flat names: the runtime registry has no label dimension)
_ENDPOINT_KEYS = {"/v1/completions": "completions",
                  "/v1/models": "models",
                  "/healthz": "healthz",
                  "/metrics": "metrics",
                  "/admin/scale": "admin_scale",
                  "/admin/drain": "admin_drain"}


class _HttpError(Exception):
    def __init__(self, code, message):
        self.code, self.message = code, message


class Gateway:
    def __init__(self, router, model_id="paddle_tpu", host="127.0.0.1",
                 port=None, poll_s=None, hb_s=None, autoscaler=None,
                 tenant_rate=None, tenant_burst=None, tenant_quota=None,
                 shed_depth=None):
        self.router = router
        # optional elastic control plane (serving_cluster/autoscale.py):
        # the health sweep drives its tick; POST /admin/scale needs it
        # (scale-up requires its spawn hook)
        self.autoscaler = autoscaler
        self.model_id = model_id
        self.host = host
        self.port = int(port if port is not None
                        else os.environ.get("PADDLE_GATEWAY_PORT",
                                            "8100"))
        self.poll_s = float(poll_s if poll_s is not None
                            else os.environ.get("PADDLE_GATEWAY_POLL_S",
                                                "0.004"))
        self.hb_s = float(hb_s if hb_s is not None
                          else os.environ.get("PADDLE_GATEWAY_HB_S",
                                              "0.25"))
        # HTTP span ring: one record per handled request, merged into
        # the cluster Perfetto export as the gateway pid's http track
        ring = int(os.environ.get("PADDLE_GATEWAY_TRACE_RING", "2048"))
        if ring < 0:
            raise ValueError(f"trace ring must be >= 0, got {ring}")
        self.trace_ring = ring
        self.http_log = deque(maxlen=max(ring, 1))
        # per-tenant admission (X-Tenant): token-bucket rate limit +
        # live-request quota. 0 disables a check; buckets/live counts
        # are pure host dicts under one lock (handlers run in executor
        # threads). Constructor args override env so tests don't need
        # the process environment (conftest leak guard).
        self.tenant_rate = float(
            tenant_rate if tenant_rate is not None
            else os.environ.get("PADDLE_TENANT_RATE", "0") or "0")
        self.tenant_burst = float(
            tenant_burst if tenant_burst is not None
            else os.environ.get("PADDLE_TENANT_BURST", "8") or "8")
        self.tenant_quota = int(
            tenant_quota if tenant_quota is not None
            else os.environ.get("PADDLE_TENANT_QUOTA", "0") or "0")
        if self.tenant_rate < 0 or self.tenant_burst < 1 \
                or self.tenant_quota < 0:
            raise ValueError(
                f"need tenant_rate >= 0, tenant_burst >= 1, "
                f"tenant_quota >= 0; got rate={self.tenant_rate} "
                f"burst={self.tenant_burst} quota={self.tenant_quota}")
        # SLO-aware shed watermark: mean queue depth above which LOW-
        # class arrivals are refused while queueing dominates the SLO
        # violations (router.qos_pressure()); 0 disables
        self.shed_depth = float(
            shed_depth if shed_depth is not None
            else os.environ.get("PADDLE_QOS_SHED_DEPTH", "0") or "0")
        self._tenant_lock = threading.Lock()
        self._buckets = {}                # tenant -> [tokens, last_ts]
        self._tenant_live = {}            # tenant -> in-flight count
        # drain serialization fallback when no autoscaler is configured
        # (with one, its _op_lock serializes drain vs tick/scale_to)
        self._drain_lock = threading.RLock()
        self._thread = None
        self._loop = None
        self._stop_evt = None

    # ------------------------------------------------------------ server
    async def serve(self, ready=None):
        """Run until ``stop()``; sets ``self.port`` to the bound port
        (port 0 = ephemeral, the tests' no-collision mode)."""
        self._loop = asyncio.get_running_loop()
        self._stop_evt = asyncio.Event()
        server = await asyncio.start_server(self._handle, self.host,
                                            self.port)
        self.port = server.sockets[0].getsockname()[1]
        health = asyncio.ensure_future(self._health_loop())
        if ready is not None:
            ready.set()
        try:
            async with server:
                await self._stop_evt.wait()
        finally:
            health.cancel()

    def start_background(self):
        """Run the event loop in a daemon thread; returns once the
        socket is listening (tests, tools, the CLI's curl demo)."""
        ready = threading.Event()
        self._thread = threading.Thread(
            target=lambda: asyncio.run(self.serve(ready)), daemon=True,
            name="gateway")
        self._thread.start()
        if not ready.wait(timeout=30):
            # name the configuration in the failure: "which port, which
            # replicas" is the first question a hung-start stack trace
            # can't answer
            raise RuntimeError(
                f"gateway failed to start within 30s "
                f"(port={self.port}, replicas="
                f"{sorted(self.router.replicas)})")
        return self

    def stop(self):
        if self._loop is not None and self._stop_evt is not None:
            self._loop.call_soon_threadsafe(self._stop_evt.set)
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # daemon thread: it cannot block exit, but a wedged
                # event loop is worth a loud line, not silence
                logging.getLogger("paddle.gateway").warning(
                    "gateway thread still alive after 10s join "
                    "(port=%s) — event loop wedged; daemon thread "
                    "will be abandoned", self.port)

    async def _health_loop(self):
        loop = asyncio.get_running_loop()
        while True:
            try:
                await loop.run_in_executor(None, self.router.refresh)
                await loop.run_in_executor(None,
                                           self.router.check_health)
                if self.autoscaler is not None:
                    # the elastic control loop rides the health sweep:
                    # one tick per sweep (hysteresis + cooldown make the
                    # effective decision cadence much slower)
                    await loop.run_in_executor(None,
                                               self.autoscaler.tick)
            except Exception:
                pass                      # the sweep must never die
            await asyncio.sleep(self.hb_s)

    # ------------------------------------------------------------- http
    def _finish_span(self, span):
        """Close one HTTP span: ring entry (the cluster trace's gateway
        track) + the endpoint+status latency histogram. A span with no
        status never got a response (client vanished pre-reply) and is
        recorded with status 0."""
        span["dur_s"] = round(time.monotonic() - span["t"], 9)
        status = span["status"] if span["status"] is not None else 0
        key = _ENDPOINT_KEYS.get(span["path"], "other")
        runtime_histogram(
            f"paddle_gateway_http_request_seconds_{key}_{status}",
            1e-6, 1e3).observe(span["dur_s"])
        if self.trace_ring:
            self.http_log.append(dict(span, status=status))

    async def _handle(self, reader, writer):
        # the span exists BEFORE the request is parsed and is filled
        # in as _read_request progresses, so a request that dies in
        # parsing (malformed request line, bad Content-Length) still
        # lands an HTTP span + histogram sample and echoes a trace id
        # on its 400; connections that never complete a request
        # (client vanished, read timeout) are not recorded
        span = {"trace_id": None, "method": "?", "path": "",
                "status": None, "gid": None, "t": time.monotonic(),
                "dur_s": None}
        record = False
        try:
            try:
                # bound the request read: a client that connects and
                # sends nothing must not pin a handler task forever
                (method, path, body, tid, prio_h,
                 tenant) = await asyncio.wait_for(
                    self._read_request(reader, writer, span),
                    timeout=30)
            except (asyncio.IncompleteReadError, ConnectionError,
                    asyncio.TimeoutError):
                return
            except _HttpError as e:
                record = True
                span["trace_id"] = span["trace_id"] or uuid.uuid4().hex
                await self._send_error(writer, e.code, e.message,
                                       span=span)
                return
            # the trace context: honor the client/proxy-minted id, else
            # mint one — every response echoes it (header + body), the
            # router threads it through every replica placement
            record = True
            span["trace_id"] = tid or uuid.uuid4().hex
            try:
                await self._route(method, path, body, writer, span,
                                  prio_h, tenant)
            except protocol.ProtocolError as e:
                await self._send_error(writer, e.code, e.message,
                                       span=span)
            except AdmissionFull as e:
                # Retry-After computed from the MEASURED queue drain
                # rate (router snapshots), floored/capped in protocol
                await self._send_error(
                    writer, "admission_full", str(e),
                    extra={"Retry-After":
                           str(self.router.retry_after_s())},
                    span=span)
            except NoReplicaError as e:
                await self._send_error(writer, "no_replica", str(e),
                                       span=span)
            except KeyError as e:
                # an unknown/already-released gid (e.g. a concurrent
                # duplicate whose twin released first) is the client's
                # 404, not a server bug
                await self._send_error(writer, "not_found",
                                       f"unknown request: {e}",
                                       span=span)
            except (ConnectionError, asyncio.CancelledError):
                raise
            except Exception as e:
                await self._send_error(writer, "internal",
                                       f"unhandled: {e!r}", span=span)
        except (ConnectionError, asyncio.CancelledError):
            pass                          # client went away mid-write
        finally:
            if record:
                self._finish_span(span)
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader, writer, span):
        line = await reader.readline()
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3:
            raise _HttpError("bad_request", "malformed request line")
        method, path = parts[0], parts[1]
        span["method"], span["path"] = method, path
        clen = 0
        expect_continue = False
        trace_id = None
        prio = None
        tenant = None
        while True:
            h = (await reader.readline()).decode("latin-1").strip()
            if not h:
                break
            k, _, v = h.partition(":")
            key = k.strip().lower()
            if key == "content-length":
                try:
                    clen = int(v)
                except ValueError:
                    raise _HttpError("bad_request",
                                     "bad Content-Length")
            elif key == "expect" \
                    and v.strip().lower() == "100-continue":
                expect_continue = True
            elif key == protocol.TRACE_HEADER.lower():
                trace_id = v.strip() or None
                span["trace_id"] = trace_id
            elif key == protocol.PRIORITY_HEADER.lower():
                # QoS class hint; the body's "priority" field wins
                # (protocol.parse_completion_request), validation
                # happens there too
                prio = v.strip() or None
            elif key == protocol.TENANT_HEADER.lower():
                tenant = v.strip() or None
        if not 0 <= clen <= _MAX_BODY:
            # the lower bound matters too: readexactly(-1) raises an
            # unhandled ValueError instead of a clean 400
            raise _HttpError("bad_request",
                             f"Content-Length must be in [0, "
                             f"{_MAX_BODY}], got {clen}")
        if expect_continue and clen:
            # curl sends Expect: 100-continue for bodies > 1 KiB and
            # waits ~1s for this interim reply before transmitting —
            # without it every large-prompt POST eats a fixed stall
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()
        body = await reader.readexactly(clen) if clen else b""
        return method, path, body, trace_id, prio, tenant

    async def _route(self, method, path, body, writer, span,
                     prio_h=None, tenant=None):
        if method == "GET" and path == "/healthz":
            alive = len(self.router.alive_names())
            total = len(self.router.replicas)
            status = ("ok" if alive == total
                      else "degraded" if alive else "down")
            # gray-failure verdicts ride the probe payload: a replica
            # can be alive (heartbeating) yet shed from placement —
            # operators see WHICH one and WHY without a /metrics scrape
            loop = asyncio.get_running_loop()
            health = await loop.run_in_executor(
                None, self.router.health_status)
            await self._send_json(writer, 200 if alive else 503, {
                "status": status, "replicas_alive": alive,
                "replicas_total": total,
                "replicas": {
                    n: {"verdict": st["verdict"],
                        "breaker": st["breaker"],
                        "signal_s": st["signal_s"]}
                    for n, st in sorted(health.items())}}, span=span)
        elif method == "GET" and path == "/v1/models":
            await self._send_json(writer, 200, {
                "object": "list",
                "data": [{"id": self.model_id, "object": "model",
                          "owned_by": "paddle_tpu"}]}, span=span)
        elif method == "GET" and path == "/metrics":
            loop = asyncio.get_running_loop()
            text = await loop.run_in_executor(
                None, self.router.metrics_prometheus)
            await self._send_raw(
                writer, 200, text.encode(),
                ctype="text/plain; version=0.0.4; charset=utf-8",
                span=span)
        elif method == "POST" and path == "/v1/completions":
            await self._completions(body, writer, span, prio_h, tenant)
        elif path == "/admin/scale" and method in ("GET", "POST"):
            await self._admin_scale(method, body, writer, span)
        elif method == "POST" and path == "/admin/drain":
            await self._admin_drain(body, writer, span)
        else:
            await self._send_error(writer, "not_found",
                                   f"no route {method} {path}",
                                   span=span)

    # ------------------------------------------------------------ admin
    def _scale_status(self):
        """The /admin/scale payload (protocol.SCALE_FIELDS): the
        router's elastic counters + the autoscaler's bounds (null when
        no autoscaler is configured — the field SET never varies)."""
        st = self.router.scale_status()
        a = self.autoscaler
        st["autoscaler"] = a is not None
        st["min_replicas"] = None if a is None else a.min_replicas
        st["max_replicas"] = None if a is None else a.max_replicas
        return st

    async def _admin_scale(self, method, body, writer, span):
        loop = asyncio.get_running_loop()
        if method == "GET":
            await self._send_json(writer, 200, self._scale_status(),
                                  span=span)
            return
        if self.autoscaler is None:
            raise protocol.ProtocolError(
                "conflict", "no autoscaler configured — manual scaling "
                "needs a spawn hook (Gateway(autoscaler=...))")
        try:
            obj = json.loads(body.decode() or "null")
        except (ValueError, UnicodeDecodeError) as e:
            raise protocol.ProtocolError("bad_request",
                                         f"body is not JSON: {e}")
        n = (obj or {}).get("replicas") if isinstance(obj, dict) else None
        if isinstance(n, bool) or not isinstance(n, int) or n < 1:
            raise protocol.ProtocolError(
                "bad_request", "'replicas' must be a positive integer")
        # the walk migrates live sessions on scale-down — run it in the
        # executor like every other replica-touching call
        await loop.run_in_executor(None, self.autoscaler.scale_to, n)
        await self._send_json(writer, 200, self._scale_status(),
                              span=span)

    async def _admin_drain(self, body, writer, span):
        """Graceful drain of ONE named replica (rolling restarts): live
        sessions migrate off, then the replica retires. Refuses to
        drain the last placeable replica — that would orphan every
        stream it holds."""
        loop = asyncio.get_running_loop()
        try:
            obj = json.loads(body.decode() or "null")
        except (ValueError, UnicodeDecodeError) as e:
            raise protocol.ProtocolError("bad_request",
                                         f"body is not JSON: {e}")
        name = (obj or {}).get("replica") if isinstance(obj, dict) \
            else None
        if not isinstance(name, str) or not name:
            raise protocol.ProtocolError(
                "bad_request", "'replica' must be a replica name")
        summary = await loop.run_in_executor(None, self._drain_sync,
                                             name)
        await self._send_json(writer, 200, summary, span=span)

    def _drain_sync(self, name):
        """Check-and-drain ATOMICALLY under the scale-op lock: two
        concurrent drains of the last two replicas must not both pass
        the last-placeable guard (each seeing count 2) and drain the
        cluster to zero — same for a drain racing an autoscaler
        tick's scale-down."""
        lock = (self.autoscaler._op_lock if self.autoscaler is not None
                else self._drain_lock)
        with lock:
            placeable = self.router.placeable_names()
            if name not in self.router.replicas:
                raise protocol.ProtocolError(
                    "not_found", f"unknown replica {name!r}")
            if name in placeable and len(placeable) <= 1:
                raise protocol.ProtocolError(
                    "conflict", f"refusing to drain {name!r}: it is "
                    "the last placeable replica — its sessions would "
                    "have nowhere to migrate")
            return self.router.remove_replica(name)

    # -------------------------------------------------- QoS / tenants
    def _tenant_retry_after(self, tokens):
        """Retry-After from the TENANT'S OWN bucket: time for it to
        refill to one whole token at its refill rate, ceil'd and
        clamped to the protocol bounds. No refill configured -> the
        floor (the quota frees on a completion, not a clock)."""
        if self.tenant_rate <= 0:
            return protocol.RETRY_AFTER_S
        wait = math.ceil(max(1.0 - tokens, 0.0) / self.tenant_rate)
        return int(min(max(wait, protocol.RETRY_AFTER_S),
                       protocol.RETRY_AFTER_MAX_S))

    def _tenant_admit(self, tenant):
        """One admission decision for a tenant-tagged arrival: returns
        None (admitted; one bucket token consumed) or an error code +
        Retry-After seconds. Quota is checked BEFORE the bucket so a
        refused request doesn't burn rate allowance."""
        if tenant is None or (self.tenant_rate <= 0
                              and self.tenant_quota <= 0):
            return None
        now = time.monotonic()
        with self._tenant_lock:
            if self.tenant_quota > 0 \
                    and self._tenant_live.get(tenant, 0) \
                    >= self.tenant_quota:
                tok = self._buckets.get(tenant,
                                        [self.tenant_burst, now])[0]
                return ("quota_exceeded", self._tenant_retry_after(tok))
            if self.tenant_rate > 0:
                tok, last = self._buckets.get(
                    tenant, (self.tenant_burst, now))
                tok = min(self.tenant_burst,
                          tok + (now - last) * self.tenant_rate)
                if tok < 1.0:
                    self._buckets[tenant] = [tok, now]
                    return ("rate_limited", self._tenant_retry_after(tok))
                self._buckets[tenant] = [tok - 1.0, now]
            if self.tenant_quota > 0:
                self._tenant_live[tenant] = \
                    self._tenant_live.get(tenant, 0) + 1
        return None

    def _tenant_release(self, tenant):
        """Undo a quota admission when its request leaves the gateway
        (finished, errored, or the client vanished)."""
        if tenant is None or self.tenant_quota <= 0:
            return
        with self._tenant_lock:
            n = self._tenant_live.get(tenant, 0) - 1
            if n > 0:
                self._tenant_live[tenant] = n
            else:
                self._tenant_live.pop(tenant, None)

    def _should_shed(self, priority):
        """SLO-aware load shedding: refuse a LOW-class arrival when
        the cluster's mean queue depth crosses the watermark AND the
        queue-vs-service decomposition says queueing (not service
        time) is where the SLO pain comes from — only then does
        refusing new waiters actually protect the objectives. Higher
        classes are never shed here; they preempt instead."""
        if self.shed_depth <= 0 or priority != "low":
            return False
        p = self.router.qos_pressure()
        return (p["queue_mean"] >= self.shed_depth
                and p["violated_queue"] >= p["violated_service"])

    # ------------------------------------------------------ completions
    async def _completions(self, body, writer, span, prio_h=None,
                           tenant=None):
        try:
            obj = json.loads(body.decode() or "null")
        except (ValueError, UnicodeDecodeError) as e:
            raise protocol.ProtocolError("bad_request",
                                         f"body is not JSON: {e}")
        req = protocol.parse_completion_request(obj, self.model_id,
                                                priority_header=prio_h)
        loop = asyncio.get_running_loop()
        trace_id = span["trace_id"]
        # tenant admission first (cheap host arithmetic, no router
        # lock): the 429 carries the tenant's OWN bucket refill time,
        # not the cluster drain rate
        verdict = await loop.run_in_executor(None, self._tenant_admit,
                                             tenant)
        if verdict is not None:
            code, retry = verdict
            await self._send_error(
                writer, code,
                f"tenant {tenant!r} {code.replace('_', ' ')}: "
                f"rate={self.tenant_rate}/s burst={self.tenant_burst} "
                f"quota={self.tenant_quota}",
                extra={"Retry-After": str(retry)}, span=span)
            return
        try:
            if await loop.run_in_executor(None, self._should_shed,
                                          req.priority):
                # surfaces as 429 reason=overload with the drain-rate
                # Retry-After (the _handle AdmissionFull path)
                raise AdmissionFull(
                    f"shedding class={req.priority!r}: cluster queue "
                    f"depth over {self.shed_depth} with queue-"
                    "attributed SLO violations dominating")
            await self._completions_admitted(req, writer, span,
                                             trace_id, loop)
        finally:
            self._tenant_release(tenant)

    async def _completions_admitted(self, req, writer, span, trace_id,
                                    loop):
        try:
            gid = await loop.run_in_executor(
                None, lambda: self.router.submit(
                    req.prompt, request_id=req.request_id,
                    trace_id=trace_id, **req.submit_kwargs()))
        except ValueError as e:
            # engine-side validation (prompt + max_tokens exceeding the
            # ring capacity, disabled repetition penalty, ...) is a
            # MALFORMED REQUEST, not a server bug
            raise protocol.ProtocolError("bad_request", str(e))
        span["gid"] = gid
        # an idempotent repeat (request_id already live) returns the
        # ORIGINAL submission's gid — adopt its trace id so the echoed
        # header/body/SSE ids match the engine spans and router audit
        # instead of the retry's fresh id, which traces nothing. Only
        # requests carrying a request_id can be repeats, so the common
        # path skips the extra executor hop + router lock round-trip.
        if req.request_id is not None:
            canon = await loop.run_in_executor(
                None, self.router.trace_id_of, gid)
            if canon is not None and canon != trace_id:
                span["trace_id"] = trace_id = canon
        if req.stream:
            await self._stream(req, gid, writer, span)
        else:
            tokens, state = [], "running"
            try:
                while True:
                    # explicit cursor: a concurrent idempotent retry
                    # sharing this gid still sees the full stream
                    new, done, state = await loop.run_in_executor(
                        None, self.router.harvest, gid, len(tokens))
                    tokens.extend(new)
                    if done:
                        break
                    await asyncio.sleep(self.poll_s)
            finally:
                # error paths (orphaned request, client gone) must not
                # leak the assignment / the engine-side tracked record
                self.router.release(gid)
            if state == "expired":
                raise protocol.ProtocolError(
                    "deadline_exceeded",
                    f"request exceeded deadline_s="
                    f"{req.deadline_s} before completing")
            await self._send_json(writer, 200, protocol.completion_response(
                gid, self.model_id, time.time(), tokens,
                protocol.finish_reason(tokens, req.stop_token_id,
                                       False),
                len(req.prompt), trace_id=trace_id), span=span)

    async def _stream(self, req, gid, writer, span):
        """SSE: headers go out with the FIRST harvest batch, so a
        request that expires before any token still gets a clean 504
        (after the first byte the stream can only finish via
        finish_reason, OpenAI-style)."""
        loop = asyncio.get_running_loop()
        trace_id = span["trace_id"]
        started = False
        last_tok = None
        sent = 0
        try:
            while True:
                new, done, state = await loop.run_in_executor(
                    None, self.router.harvest, gid, sent)
                sent += len(new)
                if new:
                    if not started:
                        await self._send_sse_headers(writer, span)
                        started = True
                    writer.write(protocol.sse_event(
                        protocol.stream_chunk(gid, self.model_id,
                                              time.time(), new,
                                              trace_id=trace_id)))
                    last_tok = new[-1]
                    await writer.drain()
                if done:
                    break
                await asyncio.sleep(self.poll_s)
        except Exception as e:
            if not started or isinstance(e, ConnectionError):
                raise                     # clean JSON error still possible
            # headers are out: a second HTTP response would be protocol
            # garbage — terminate the STREAM honestly instead
            # (finish_reason "error" + [DONE], see protocol.py)
            writer.write(protocol.sse_event(protocol.stream_chunk(
                gid, self.model_id, time.time(), [], reason="error",
                trace_id=trace_id)))
            writer.write(protocol.SSE_DONE)
            await writer.drain()
            return
        finally:
            # a client that disconnects mid-stream must not leak the
            # router assignment (and its engine-side tracked record)
            self.router.release(gid)
        expired = state == "expired"
        if not started:
            if expired:
                raise protocol.ProtocolError(
                    "deadline_exceeded",
                    f"request exceeded deadline_s={req.deadline_s} "
                    "before its first token")
            await self._send_sse_headers(writer, span)
        reason = protocol.finish_reason(
            [] if last_tok is None else [last_tok], req.stop_token_id,
            expired)
        writer.write(protocol.sse_event(protocol.stream_chunk(
            gid, self.model_id, time.time(), [], reason=reason,
            trace_id=trace_id)))
        writer.write(protocol.SSE_DONE)
        await writer.drain()

    # ---------------------------------------------------------- writers
    async def _send_json(self, writer, status, obj, extra=None,
                         span=None):
        await self._send_raw(writer, status,
                             json.dumps(obj).encode(),
                             ctype="application/json", extra=extra,
                             span=span)

    async def _send_error(self, writer, code, message, extra=None,
                          span=None):
        status, body = protocol.error_body(code, message)
        await self._send_json(writer, status, body, extra=extra,
                              span=span)

    async def _send_sse_headers(self, writer, span=None):
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Cache-Control: no-cache\r\n")
        if span is not None:
            span["status"] = 200
            head += (f"{protocol.TRACE_HEADER}: "
                     f"{span['trace_id']}\r\n").encode()
        writer.write(head + b"Connection: close\r\n\r\n")
        await writer.drain()

    async def _send_raw(self, writer, status, payload, ctype,
                        extra=None, span=None):
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  409: "Conflict", 429: "Too Many Requests",
                  500: "Internal Server Error",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(status, "OK")
        head = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(payload)}",
                "Connection: close"]
        if span is not None:
            # every traced response echoes the trace context header —
            # the client (or a proxy) can correlate any reply, error
            # rows included, with the merged cluster trace
            span["status"] = status
            head.append(f"{protocol.TRACE_HEADER}: {span['trace_id']}")
        for k, v in (extra or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + payload)
        await writer.drain()
