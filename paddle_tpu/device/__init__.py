"""paddle.device namespace. Parity: python/paddle/device/ (incl. cuda shims).

On TPU there are no user-managed streams/events: XLA schedules async
dispatch. Stream/Event keep API shape; synchronize() blocks on all devices.
"""
from __future__ import annotations

import jax

from ..core.place import (set_device, get_device, device_count, CPUPlace,
                          TPUPlace, XLAPlace, CUDAPlace,
                          is_compiled_with_cuda, is_compiled_with_tpu)

__all__ = ["set_device", "get_device", "device_count", "synchronize",
           "Stream", "Event", "current_stream", "stream_guard", "cuda",
           "get_all_device_type", "get_available_device"]


def synchronize(device=None):
    (jax.device_put(0) + 0).block_until_ready()


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


class Stream:
    """API-parity stream: XLA owns real scheduling; operations are ordered
    program-order per device already."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        synchronize()

    def query(self):
        return True


_current = Stream()


def current_stream(device=None):
    return _current


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


class _CudaNS:
    """paddle.device.cuda.* shims routing to the accelerator (TPU)."""

    Stream = Stream
    Event = Event

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def current_stream(device=None):
        return _current

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.local_devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        return _CudaNS.max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return _CudaNS.memory_allocated(device)

    @staticmethod
    def empty_cache():
        pass


cuda = _CudaNS()
