"""paddle.onnx. Parity: python/paddle/onnx/export.py :: export — the
reference delegates to the external `paddle2onnx` converter.

This build has no ONNX exporter dependency; `export` is gated with a
clear error pointing at the portable-artifact path that DOES exist here
(`paddle.static.save_inference_model` → StableHLO `.pdmodel`, loadable
without Python model code)."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise RuntimeError(
        "paddle.onnx.export requires the paddle2onnx converter, which is "
        "not available in this environment. For a portable compiled "
        "artifact use paddle.static.save_inference_model(path, feeds, "
        "fetches) — it writes a StableHLO .pdmodel (plus .pdiparams) that "
        "loads and runs without the Python model definition.")
