"""ctypes loader for the native runtime (csrc/runtime.cc).

The shared library is built lazily with g++ on first use and cached next to
the source (pybind11 is not in this image; the C ABI + ctypes is the
binding layer — SURVEY §2.2 "Pybind bindings" altitude). Every consumer
must degrade gracefully when the toolchain is unavailable:
`load_native()` returns None and the pure-Python fallbacks take over.

Native components exposed here:
  TCPStore / TCPStoreServer  — rendezvous KV
      (parity: paddle/fluid/distributed/store/tcp_store.cc :: TCPStore,
      MasterDaemon)
  NativeTracer               — host span collector -> chrome trace
      (parity: paddle/fluid/platform/profiler/ host tracer)
  NativeQueue                — bounded blocking queue; DataLoader prefetch
      (parity: the reference's native buffered-reader machinery)
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LIB_LOCK = threading.Lock()
_BUILD_FAILED = False


def _src_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "csrc", "runtime.cc")


def _lib_path() -> str:
    return os.path.join(os.path.dirname(_src_path()),
                        "libpaddle_tpu_runtime.so")


def load_native():
    """Build (once) and dlopen the runtime; None if unavailable."""
    global _LIB, _BUILD_FAILED
    if _LIB is not None:
        return _LIB
    if _BUILD_FAILED or os.environ.get("PADDLE_TPU_NO_NATIVE") == "1":
        return None
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src, lib = _src_path(), _lib_path()
        try:
            if (not os.path.exists(lib)
                    or os.path.getmtime(lib) < os.path.getmtime(src)):
                # pid-unique scratch: concurrently-launched ranks all see
                # the stale .so and rebuild; a shared ".tmp" makes them
                # clobber each other's half-written output (os.replace of
                # a file another rank is still writing), taking the
                # native runtime down for the whole job
                tmp = f"{lib}.{os.getpid()}.tmp"
                try:
                    subprocess.run(
                        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                         "-pthread", src, "-o", tmp],
                        check=True, capture_output=True, timeout=120)
                    os.replace(tmp, lib)
                finally:
                    if os.path.exists(tmp):
                        os.remove(tmp)
            L = ctypes.CDLL(lib)
        except Exception:
            _BUILD_FAILED = True
            return None
        # signatures
        L.pd_store_master_start.restype = ctypes.c_void_p
        L.pd_store_master_start.argtypes = [ctypes.c_int]
        L.pd_store_master_port.restype = ctypes.c_int
        L.pd_store_master_port.argtypes = [ctypes.c_void_p]
        L.pd_store_master_stop.argtypes = [ctypes.c_void_p]
        L.pd_store_client_connect.restype = ctypes.c_void_p
        L.pd_store_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                              ctypes.c_int]
        L.pd_store_client_close.argtypes = [ctypes.c_void_p]
        L.pd_store_set.restype = ctypes.c_int
        L.pd_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int]
        L.pd_store_get.restype = ctypes.c_int
        L.pd_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int]
        L.pd_store_add.restype = ctypes.c_int
        L.pd_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_longlong,
                                   ctypes.POINTER(ctypes.c_longlong)]
        L.pd_store_wait.restype = ctypes.c_int
        L.pd_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
        L.pd_trace_enable.argtypes = [ctypes.c_int]
        L.pd_trace_begin.argtypes = [ctypes.c_char_p]
        L.pd_trace_count.restype = ctypes.c_int
        L.pd_trace_dump.restype = ctypes.c_int
        L.pd_trace_dump.argtypes = [ctypes.c_char_p]
        L.pd_queue_new.restype = ctypes.c_void_p
        L.pd_queue_new.argtypes = [ctypes.c_int]
        L.pd_queue_close.argtypes = [ctypes.c_void_p]
        L.pd_queue_free.argtypes = [ctypes.c_void_p]
        L.pd_queue_put.restype = ctypes.c_int
        L.pd_queue_put.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int]
        L.pd_queue_get.restype = ctypes.c_void_p
        L.pd_queue_get.argtypes = [ctypes.c_void_p, ctypes.c_int]
        L.pd_queue_size.restype = ctypes.c_int
        L.pd_queue_size.argtypes = [ctypes.c_void_p]
        _LIB = L
        return L


class TCPStoreServer:
    """Master daemon; bind port 0 for an ephemeral port (read .port)."""

    def __init__(self, port: int = 0):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pd_store_master_start(port)
        if not self._h:
            raise OSError(f"TCPStoreServer: cannot bind port {port}")
        self.port = lib.pd_store_master_port(self._h)

    def stop(self):
        if self._h:
            self._lib.pd_store_master_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client with the reference TCPStore surface: set/get/add/wait."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pd_store_client_connect(
            host.encode(), port, int(timeout_s * 1000))
        if not self._h:
            raise ConnectionError(f"TCPStore: cannot reach {host}:{port}")

    def set(self, key: str, value: bytes):
        if self._lib.pd_store_set(self._h, key.encode(), value,
                                  len(value)) != 0:
            raise IOError("store set failed")

    def get(self, key: str, max_len: int = 1 << 20):
        buf = ctypes.create_string_buffer(max_len)
        n = self._lib.pd_store_get(self._h, key.encode(), buf, max_len)
        if n < 0:
            return None
        # value larger than the buffer: the C side reports the full length —
        # retry with an exact-size buffer instead of silently truncating
        # (loop: the value may have grown again between calls)
        for _ in range(4):
            if n <= max_len:
                return buf.raw[:n]
            max_len = n
            buf = ctypes.create_string_buffer(max_len)
            n = self._lib.pd_store_get(self._h, key.encode(), buf, max_len)
            if n < 0:
                return None
        raise IOError(f"store get: value for {key!r} keeps growing")

    def add(self, key: str, delta: int) -> int:
        out = ctypes.c_longlong()
        if self._lib.pd_store_add(self._h, key.encode(), delta,
                                  ctypes.byref(out)) != 0:
            raise IOError("store add failed")
        return out.value

    def wait(self, key: str, timeout_s: float = 30.0):
        if self._lib.pd_store_wait(self._h, key.encode(),
                                   int(timeout_s * 1000)) != 0:
            raise TimeoutError(f"store wait({key}) timed out")

    def close(self):
        if self._h:
            self._lib.pd_store_client_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeTracer:
    """Host span collector; None-safe module-level helpers in profiler.

    Lazy: the (possibly slow, g++-invoking) load_native() runs on first
    use, never at construction — so importing a module that instantiates a
    tracer costs nothing."""

    def __init__(self):
        self._lib_loaded = False
        self.__lib = None

    @property
    def _lib(self):
        if not self._lib_loaded:
            self.__lib = load_native()
            self._lib_loaded = True
        return self.__lib

    @property
    def available(self) -> bool:
        return self._lib is not None

    def enable(self, on: bool = True):
        if self._lib:
            self._lib.pd_trace_enable(1 if on else 0)

    def begin(self, name: str):
        if self._lib:
            self._lib.pd_trace_begin(name.encode())

    def end(self):
        if self._lib:
            self._lib.pd_trace_end()

    def count(self) -> int:
        return self._lib.pd_trace_count() if self._lib else 0

    def dump(self, path: str) -> bool:
        return bool(self._lib) and \
            self._lib.pd_trace_dump(path.encode()) == 0


class NativeQueue:
    """Bounded blocking queue of integer tokens (1-based; 0 is reserved)."""

    def __init__(self, capacity: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pd_queue_new(capacity)

    def put(self, token: int, timeout_s: float = 10.0) -> bool:
        assert token > 0
        return self._lib.pd_queue_put(self._h, ctypes.c_void_p(token),
                                      int(timeout_s * 1000)) == 0

    def get(self, timeout_s: float = 10.0):
        r = self._lib.pd_queue_get(self._h, int(timeout_s * 1000))
        return None if not r else int(r)

    def qsize(self) -> int:
        return self._lib.pd_queue_size(self._h)

    def close(self):
        if self._h:
            self._lib.pd_queue_close(self._h)

    def free(self):
        if self._h:
            self._lib.pd_queue_close(self._h)
            self._lib.pd_queue_free(self._h)
            self._h = None
