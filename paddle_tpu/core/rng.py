"""Global-seed facade over JAX PRNG keys.

Parity: ``paddle.seed`` + Fleet's ``RNGStatesTracker``
(python/paddle/distributed/fleet/layers/mpu/random.py). JAX's explicit keys
are stronger than the reference's global-seed model; this facade keeps the
Paddle-shaped API while every draw splits the global key.

TPU/jit-critical design: the key lives in a persistent Tensor, so
``paddle.jit.to_static`` functionalizes it like any parameter — random ops
inside a compiled train step thread the key through the program instead of
baking a trace-time constant (each call gets fresh randomness).
"""
from __future__ import annotations

import contextlib
import threading

import jax


def _impl() -> str:
    """PRNG implementation for all framework keys. Default "rbg": threefry
    split/fold semantics, but the bit draws lower to XLA RngBitGenerator —
    the TPU's hardware generator, far cheaper than threefry's ALU rounds and
    fusable into the consuming elementwise op (dropout masks cost ~0 extra
    HBM). Override with PADDLE_TPU_PRNG_IMPL=threefry2x32 for JAX-default
    bitstreams."""
    import os
    return os.environ.get("PADDLE_TPU_PRNG_IMPL", "rbg")


def _key(s: int):
    return jax.random.key(int(s), impl=_impl())


class _RngState(threading.local):
    def __init__(self):
        self.key_tensor = None
        self.seed_value = 0

    def ensure(self):
        # `_data is None` = the key was lazily created inside a to_static
        # trace that failed; the rollback (jit _execute) killed it. Rebuild
        # from the last seed so the retry reruns with live, tracked state.
        # A DELETED device array (bench.py's inter-config memory release
        # hard-deletes all live arrays) rebuilds the same way.
        dead = (self.key_tensor is None or self.key_tensor._data is None)
        if not dead:
            is_del = getattr(self.key_tensor._data, "is_deleted", None)
            try:
                dead = bool(is_del()) if callable(is_del) else False
            except Exception:
                dead = False   # tracer mid-trace: live by definition
        if dead:
            from ..tensor.tensor import Tensor, register_persistent
            self.key_tensor = Tensor(_key(self.seed_value))
            self.key_tensor.name = "global_rng_key"
            self.key_tensor.persistable = True
            register_persistent(self.key_tensor)
        return self.key_tensor


_rng = _RngState()


def seed(s: int):
    t = _rng.ensure()
    t._data = _key(s)
    _rng.seed_value = int(s)
    return t


def get_seed() -> int:
    return _rng.seed_value


def next_key():
    """Fresh subkey; global key advances (threads through jit as state)."""
    t = _rng.ensure()
    k1, k2 = jax.random.split(t._data)
    t._data = k1
    return k2


def next_threefry_key():
    """Fresh subkey guaranteed to be threefry — for the few jax.random
    samplers (poisson) not implemented for the rbg impl. Derived
    deterministically from the global stream regardless of its impl."""
    k = next_key()
    if str(jax.random.key_impl(k)) == "threefry2x32":
        return k
    bits = jax.random.bits(k, (2,), "uint32")
    return jax.random.wrap_key_data(bits, impl="threefry2x32")


def get_rng_state():
    return _rng.ensure()._data


def set_rng_state(state):
    t = _rng.ensure()
    if isinstance(state, int):
        t._data = _key(state)
    else:
        t._data = state


class RNGStatesTracker:
    """Named RNG streams (model-parallel dropout determinism).

    Parity: fleet/layers/mpu/random.py :: RNGStatesTracker. add() registers a
    named stream with its own seed; rng_state(name) switches draws to it.
    """

    def __init__(self):
        self.states_: dict[str, object] = {}
        self.seeds_: set[int] = set()

    def reset(self):
        self.states_.clear()
        self.seeds_.clear()

    def add(self, name: str, seed_: int):
        if seed_ in self.seeds_:
            raise ValueError(f"seed {seed_} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed_)
        self.states_[name] = _key(seed_)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel_rng"):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        t = _rng.ensure()
        orig = t._data
        t._data = self.states_[name]
        try:
            yield
        finally:
            self.states_[name] = t._data
            t._data = orig


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _RNG_TRACKER


MODEL_PARALLEL_RNG = "model_parallel_rng"


def model_parallel_random_seed(seed_: int = 100):
    """TP dropout determinism: global stream shared, mp stream offset by the
    tensor-parallel rank (reference: mpu/random.py :: model_parallel_random_seed)."""
    import random as _pyrandom
    global_seed = seed_
    local_seed = seed_ + 1024
    try:
        from ..distributed.fleet.base.topology import _HYBRID_GROUP
        if _HYBRID_GROUP[0] is not None:
            local_seed = seed_ + 1024 + _HYBRID_GROUP[0].get_model_parallel_rank()
    except Exception:
        pass
    _RNG_TRACKER.reset()
    seed(global_seed)
    _pyrandom.seed(global_seed)
    _RNG_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
