"""paddle.quantization — QAT / PTQ over fake-quant with a straight-through
estimator.

Parity: python/paddle/quantization/ (config.py :: QuantConfig; qat.py :: QAT;
ptq.py :: PTQ; quanters/abs_max.py :: FakeQuanterWithAbsMaxObserver;
observers/abs_max.py :: AbsmaxObserver; factory.py).

TPU-first: fake-quant is `x + stop_gradient(dequant(quant(x)) - x)` — the
STE falls out of the identity path with zero custom-vjp machinery, and XLA
fuses the round/clip chain into neighbouring ops. int8 inference on TPU is
then a matter of feeding the learned scales to XLA's native int8 matmul."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear
from ..tensor.tensor import Tensor, apply_op

__all__ = ["QuantConfig", "QAT", "PTQ", "QuanterFactory",
           "FakeQuanterWithAbsMaxObserver", "AbsmaxObserver",
           "quanter", "QuantedLinear"]


def _fake_quant(x, scale, bit_length):
    """Quantize→dequantize with STE. scale maps absmax → qmax."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    dq = q * s / qmax
    return x + jax.lax.stop_gradient(dq - x)


class BaseQuanter(Layer):
    """A quanter is a Layer inserted into the forward path that fake-
    quantizes what flows through it and tracks the scale it uses."""

    def scales(self) -> Tensor:
        raise NotImplementedError

    def bit_length(self) -> int:
        return self._bit_length


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average absmax fake quanter (the reference QAT default).

    The scale and its initialized flag live as PERSISTENT in-graph state
    (like optimizer accumulators), so a QAT run executed entirely under
    jit/to_static functionalizes the moving-average update and ends with
    a calibrated scale — the same numbers as the eager path."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype: str = "float32", name=None):
        super().__init__()
        from ..tensor.tensor import register_persistent
        self._moving_rate = float(moving_rate)
        self._bit_length = int(bit_length)
        self._scale_state = Tensor(jnp.zeros((), jnp.float32))
        self._init_state = Tensor(jnp.zeros((), jnp.float32))
        for t in (self._scale_state, self._init_state):
            t.stop_gradient = True
            register_persistent(t)

    # back-compat surface (PTQ.convert writes these as host values)
    @property
    def _scale(self):
        return float(np.asarray(self._scale_state._data)) \
            if not isinstance(self._scale_state._data, jax.core.Tracer) \
            else self._scale_state._data

    @_scale.setter
    def _scale(self, v):
        self._scale_state._data = jnp.asarray(v, jnp.float32)

    @property
    def _initialized(self):
        d = self._init_state._data
        return bool(np.asarray(d) > 0) \
            if not isinstance(d, jax.core.Tracer) else d > 0

    @_initialized.setter
    def _initialized(self, v):
        self._init_state._data = jnp.asarray(1.0 if v else 0.0, jnp.float32)

    def forward(self, x: Tensor) -> Tensor:
        bl = self._bit_length
        r = self._moving_rate
        init = self._init_state._data
        prev = self._scale_state._data
        if not self.training and not isinstance(init, jax.core.Tracer) \
                and bool(np.asarray(init) > 0):
            # calibrated + frozen: inference hot path — no absmax reduction,
            # no state writes (keeps state out of traced eval graphs too)
            return apply_op(lambda a: _fake_quant(a, prev, bl), x)
        cur = jnp.max(jnp.abs(jax.lax.stop_gradient(x._data))).astype(
            jnp.float32)
        if self.training:
            # moving-average update, branch-free so it traces: first batch
            # seeds the scale, later batches blend
            scale = jnp.where(init > 0, r * prev + (1 - r) * cur, cur)
        else:
            # eval before any calibration initializes from this batch
            # (never quantize with a zero scale)
            scale = jnp.where(init > 0, prev, cur)
        self._scale_state._data = scale
        self._init_state._data = jnp.ones((), jnp.float32)
        return apply_op(lambda a: _fake_quant(a, scale, bl), x)

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._scale_state._data, jnp.float32))


class AbsmaxObserver(BaseQuanter):
    """PTQ observer: passes activations through untouched while recording
    the running absmax; `scales()` feeds the convert step."""

    def __init__(self, quant_bits: int = 8, name=None):
        super().__init__()
        self._bit_length = int(quant_bits)
        self._scale = 0.0

    def forward(self, x: Tensor) -> Tensor:
        if isinstance(x._data, jax.core.Tracer):
            # calibration must run eagerly to observe ranges; under trace
            # the observer is a no-op pass-through
            return x
        cur = float(np.asarray(jnp.max(jnp.abs(x._data))))
        self._scale = max(self._scale, cur)
        return x

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._scale, jnp.float32))


class QuanterFactory:
    """Bind a quanter class + kwargs for later instantiation (the
    reference's factory.py contract)."""

    def __init__(self, cls, **kwargs):
        self._cls = cls
        self._kwargs = kwargs

    def instance(self) -> BaseQuanter:
        return self._cls(**self._kwargs)


def quanter(cls=None, **kwargs) -> QuanterFactory:
    if cls is None:
        cls = FakeQuanterWithAbsMaxObserver
    return QuanterFactory(cls, **kwargs)


class QuantConfig:
    """Which layers get which activation/weight quanters."""

    def __init__(self, activation: QuanterFactory | None = None,
                 weight: QuanterFactory | None = None):
        self._global_act = activation
        self._global_weight = weight
        self._layer_cfg: list[tuple[object, QuanterFactory | None,
                                    QuanterFactory | None]] = []
        self._type_cfg: list[tuple[type, QuanterFactory | None,
                                   QuanterFactory | None]] = []

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg.append((l, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg.append((t, activation, weight))

    def _config_for(self, layer: Layer):
        for l, a, w in self._layer_cfg:
            if l is layer:
                return a, w
        for t, a, w in self._type_cfg:
            if isinstance(layer, t):
                return a, w
        if self._global_act is not None or self._global_weight is not None:
            if isinstance(layer, tuple(_QAT_MAPPING)):
                return self._global_act, self._global_weight
        return None, None


class QuantedLinear(Layer):
    """Linear with fake-quantized input activations and weight — the QAT
    stand-in the reference swaps in (nn/quant/qat/linear.py)."""

    def __init__(self, inner: Linear, act_factory, weight_factory):
        super().__init__()
        self.inner = inner
        self.activation_quanter = (act_factory.instance()
                                   if act_factory else None)
        self.weight_quanter = (weight_factory.instance()
                               if weight_factory else None)

    def forward(self, x):
        from ..nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.inner.bias)


_QAT_MAPPING: dict[type, type] = {Linear: QuantedLinear}


def _swap_layers(model: Layer, config: QuantConfig, observers_only: bool):
    for holder in model.sublayers(include_self=True):
        for name, sub in list(holder._sub_layers.items()):
            if sub is None or isinstance(sub, (BaseQuanter, QuantedLinear)):
                continue
            a, w = config._config_for(sub)
            if a is None and w is None:
                continue
            quanted_cls = next(
                (qc for base, qc in _QAT_MAPPING.items()
                 if isinstance(sub, base)), None)
            if quanted_cls is None:
                import warnings
                warnings.warn(
                    f"layer {type(sub).__name__} is configured for "
                    f"quantization but has no quanted mapping; skipped")
                continue
            if observers_only:
                a = a or quanter(AbsmaxObserver)
                w = w or quanter(AbsmaxObserver)
            holder._sub_layers[name] = quanted_cls(sub, a, w)
    return model


class QAT:
    """Quantization-aware training driver: `quantize` swaps supported
    layers for fake-quanted versions per the config."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        assert inplace, ("TPU build swaps sublayers in place; copy the "
                         "model first for inplace=False semantics")
        return _swap_layers(model, self._config, observers_only=False)


class PTQ:
    """Post-training quantization: observers record ranges during
    calibration forward passes; `convert` freezes scales into fake-quant."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        assert inplace, "see QAT.quantize"
        return _swap_layers(model, self._config, observers_only=True)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace each observer with a frozen FakeQuanter at the observed
        scale (eval-mode: scale no longer updates)."""
        assert inplace, "see QAT.quantize"
        for holder in model.sublayers(include_self=True):
            for sub in holder._sub_layers.values():
                if isinstance(sub, QuantedLinear):
                    for attr in ("activation_quanter", "weight_quanter"):
                        obs = getattr(sub, attr)
                        if isinstance(obs, AbsmaxObserver):
                            fq = FakeQuanterWithAbsMaxObserver(
                                bit_length=obs._bit_length)
                            fq._scale = obs._scale
                            fq._initialized = True
                            fq.eval()
                            setattr(sub, attr, fq)
                            sub._sub_layers[attr] = fq
        model.eval()
        return model
