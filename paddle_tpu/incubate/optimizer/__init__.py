"""Incubate optimizers: LookAhead and ModelAverage.

Capability parity: python/paddle/incubate/optimizer/{lookahead.py ::
LookAhead, modelaverage.py :: ModelAverage}. TPU-style: the slow-weight /
running-average state lives in plain jnp arrays registered as persistent
(so the wrappers functionalize under jit.to_static like optimizer
accumulators), and the k-step / window logic is host-side Python — it
gates which compiled step runs, it is not data-dependent control flow
inside a trace.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor.tensor import Tensor, register_persistent

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """Lookahead wrapper: every k fast steps, slow <- slow + alpha *
    (fast - slow) and fast <- slow (reference: lookahead.py)."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = {}          # param uid -> slow-weight Tensor
        # seed slow weights from the INITIAL params now — lazily creating
        # them at the first sync would seed slow = fast and silently drop
        # the whole first-window pullback toward w0
        for p in self._params():
            self._slow_for(p)

    def __getattr__(self, kk):
        return getattr(self.inner_optimizer, kk)

    def _params(self):
        return getattr(self.inner_optimizer, "_parameter_list", None) or []

    def _slow_for(self, p):
        s = self._slow.get(p._uid)
        if s is None or s._data is None:
            s = Tensor(p._data)
            s.name = (getattr(p, "name", None) or "param") + "@SLOW"
            s.persistable = True
            s.stop_gradient = True
            register_persistent(s)
            self._slow[p._uid] = s
        return s

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            a = self.alpha
            for p in self._params():
                s = self._slow_for(p)
                new_slow = s._data.astype(jnp.float32) * (1 - a) + \
                    p._data.astype(jnp.float32) * a
                s._data = new_slow.astype(s._data.dtype)
                p._data = new_slow.astype(p._data.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@LookAhead.step_count"] = self._step_count
        for p in self._params():
            s = self._slow.get(p._uid)
            if s is not None:
                sd[s.name] = s
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)   # never mutate the caller's dict: it may feed a
        #                 second consumer or be re-saved
        self._step_count = int(sd.pop("@LookAhead.step_count",
                                      self._step_count))
        for p in self._params():
            key = (getattr(p, "name", None) or "param") + "@SLOW"
            if key in sd:
                t = sd.pop(key)
                self._slow_for(p)._data = jnp.asarray(
                    t._data if isinstance(t, Tensor) else t)
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage:
    """Running parameter average with apply()/restore() swap windows
    (reference: modelaverage.py — simplified to a uniform running mean
    over an accumulation window, the dominant use: evaluate with averaged
    weights, restore, continue training)."""

    def __init__(self, average_window_rate: float = 0.15, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000000, name=None):
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._params = list(parameters) if parameters is not None else []
        self._sum = {}
        self._count = 0
        self._backup = None

    def _acc_for(self, p):
        s = self._sum.get(p._uid)
        if s is None or s._data is None:
            s = Tensor(jnp.zeros_like(p._data, jnp.float32))
            s.name = (getattr(p, "name", None) or "param") + "@AVG_SUM"
            s.persistable = True
            s.stop_gradient = True
            register_persistent(s)
            self._sum[p._uid] = s
        return s

    def step(self):
        """Accumulate the current weights (call after optimizer.step);
        the window restarts when it exceeds max_average_window * rate or
        max_average_window, per the reference's window rules simplified
        to a hard cap."""
        cap = max(self.min_w, min(self.max_w,
                                  int(self.max_w * self.rate) or 1))
        if self._count >= cap:
            self._count = 0
            for p in self._params:
                self._acc_for(p)._data = jnp.zeros_like(
                    p._data, jnp.float32)
        for p in self._params:
            s = self._acc_for(p)
            s._data = s._data + p._data.astype(jnp.float32)
        self._count += 1

    def apply(self, executor=None, need_restore: bool = True):
        """Swap the averaged weights in (context-manager compatible).
        Idempotent: a second apply() without restore() is a no-op — it
        must NOT overwrite the backup with the averaged weights."""
        if self._count == 0 or self._backup is not None:
            return self
        self._backup = {p._uid: p._data for p in self._params}
        inv = 1.0 / float(self._count)
        for p in self._params:
            s = self._sum[p._uid]
            p._data = (s._data * inv).astype(p._data.dtype)
        if not need_restore:
            self._backup = None
        return self

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            if p._uid in self._backup:
                p._data = self._backup[p._uid]
        self._backup = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.restore()
        return False
