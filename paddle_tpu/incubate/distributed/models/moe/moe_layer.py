"""MoE layer with expert parallelism.

Parity: python/paddle/incubate/distributed/models/moe/moe_layer.py ::
MoELayer (+ the global_scatter/global_gather CUDA alltoall ops of
paddle/fluid/operators/collective/ and utils count/limit/prune ops).

TPU-native design: experts live as STACKED parameters [E, ...] whose expert
dim is sharded over the data axes of the mesh (expert parallelism rides the
same chips as dp, as ERNIE's Fleet config does). Token dispatch/combine are
dense einsums against the gate's capacity masks — under jit, GSPMD lowers the
sharded einsum into exactly the all-to-all exchange the reference's
global_scatter/global_gather kernels perform, scheduled on ICI.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....nn import functional as F
from .....nn.initializer import Normal
from .....nn.layer.layers import Layer
from .....tensor.tensor import Parameter, Tensor, apply_op
from .gate import GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer", "ExpertMLP"]


class ExpertMLP(Layer):
    """Stacked expert FFN: weights [E, d, h] / [E, h, d], vmapped over E."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        init = Normal(0.0, 0.02)
        self.w1 = Parameter(init((num_experts, d_model, d_hidden),
                                 jnp.float32))
        self.b1 = Parameter(jnp.zeros((num_experts, d_hidden), jnp.float32))
        self.w2 = Parameter(init((num_experts, d_hidden, d_model),
                                 jnp.float32))
        self.b2 = Parameter(jnp.zeros((num_experts, d_model), jnp.float32))
        # expert dim sharded over the dp axis (expert parallelism)
        for p in (self.w1, self.b1, self.w2, self.b2):
            p.sharding_spec = P("dp")
            p.is_distributed = True
        self.activation = activation

    def forward(self, expert_inputs):
        """expert_inputs [E, B, C, d] → [E, B, C, d]."""
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self.activation]

        def f(x, w1, b1, w2, b2):
            h = jnp.einsum("ebcd,edh->ebch", x, w1) + b1[:, None, None, :]
            h = act(h)
            return jnp.einsum("ebch,ehd->ebcd", h, w2) + b2[:, None, None, :]
        return apply_op(f, expert_inputs, self.w1, self.b1, self.w2, self.b2)


class MoELayer(Layer):
    """Parity: MoELayer(d_model, experts, gate="gshard", top_k, ...).

    forward(x): [B, S, d] → [B, S, d]; aux (load-balance) loss accumulates on
    self.gate.aux_loss — add `moe.gate.aux_loss * coeff` to the train loss as
    the reference does.
    """

    def __init__(self, d_model, d_hidden=None, num_experts=8, experts=None,
                 gate="gshard", top_k=2, capacity_factor=1.2,
                 group=None, recompute_interval=0, activation="gelu",
                 moe_group=None, mp_group=None, **kw):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        if isinstance(gate, str):
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gate]
            if gate == "switch":
                top_k = 1
            self.gate = cls(d_model, num_experts,
                            capacity_factor=capacity_factor)
        else:
            self.gate = gate
        self.experts = experts or ExpertMLP(num_experts, d_model,
                                            d_hidden or 4 * d_model,
                                            activation)

    def forward(self, x):
        import os
        if os.environ.get("PADDLE_TPU_MOE_IDENTITY_DISPATCH") == "1":
            # BENCHMARK PROBE, not a routing mode: fixed round-robin
            # chunking replaces the gate + mask einsums, keeping the
            # expert compute (shapes included) identical — full-step time
            # minus this twin's time isolates the gate+dispatch+combine
            # cost, the decomposition BASELINE configs[4] names (reference
            # metric: global_scatter/global_gather alltoall step time)
            b, s, d = x.shape[0], x.shape[1], x.shape[2]
            e = self.num_experts
            # the gate never runs in this probe: clear any aux_loss a
            # PREVIOUS trace left behind (a stale tracer would leak
            # into this trace via the model's aux_loss() sum)
            self.gate.aux_loss = None
            # SAME capacity as the real gate so the expert compute is
            # genuinely shape-identical ([E, B, gate.capacity(s), d]) —
            # tokens round-robin into the e*cap slots, zero-padded
            cap = self.gate.capacity(s)

            def rr(xx):
                slots = e * cap
                pad = slots - s
                xp = jnp.pad(xx, ((0, 0), (0, max(pad, 0)), (0, 0))) \
                    if pad > 0 else xx[:, :slots]
                ei = xp.reshape(xx.shape[0], e, cap, xx.shape[2])
                return jnp.swapaxes(ei, 0, 1)            # [E,B,C,d]
            expert_in = apply_op(rr, x)
            expert_out = self.experts(expert_in)

            def rr_inv(eo):
                back = jnp.swapaxes(eo, 0, 1)            # [B,E,C,d]
                back = back.reshape(back.shape[0], e * cap, d)
                if e * cap >= s:
                    return back[:, :s]
                return jnp.pad(back, ((0, 0), (0, s - e * cap), (0, 0)))
            return apply_op(rr_inv, expert_out)
        combine, dispatch, aux = self.gate(x)  # [B,S,E,C] masks

        def dispatch_fn(xx, dd):
            return jnp.einsum("bsec,bsd->ebcd", dd, xx)
        expert_in = apply_op(dispatch_fn, x, dispatch)
        expert_out = self.experts(expert_in)   # [E,B,C,d]

        def combine_fn(cc, eo):
            return jnp.einsum("bsec,ebcd->bsd", cc, eo)
        out = apply_op(combine_fn, combine, expert_out)
        return out
