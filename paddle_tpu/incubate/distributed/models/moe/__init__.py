"""paddle.incubate.distributed.models.moe parity surface."""
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate
from .moe_layer import MoELayer, ExpertMLP
from .grad_clip import ClipGradForMOEByGlobalNorm
