"""Kernel autotune config surface.

Capability parity: python/paddle/incubate/autotune.py :: set_config —
the reference toggles kernel-algorithm search (cuDNN algo search, layout
autotune, dataloader tuning). TPU-native meaning: XLA already autotunes
its own fusions; the tunable surface HERE is the Pallas flash-attention
tiling (PADDLE_TPU_FLASH_BQ/BK — swept by tools/attn_sweep.py) and the
dataloader worker count. set_config maps the reference's {"kernel":
{"enable": ..}, "dataloader": {...}} dict onto those knobs and records
the config for get_config() introspection.
"""
from __future__ import annotations

import json
import os

__all__ = ["set_config", "get_config"]

_config = {"kernel": {"enable": False},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}


def set_config(config=None):
    """Accepts a dict (or a path to a JSON file, like the reference).

    kernel.enable=True with optional kernel.tuning_range [bq, bk] pins
    the flash tile caps via the PADDLE_TPU_FLASH_* env the kernels read
    at trace time."""
    global _config
    if config is None:
        # reset-to-enabled-defaults: no tuning_range anymore, so the tile
        # caps must unpin too (kernels read env at trace time)
        _config = {k: {"enable": True} for k in _config}
        os.environ.pop("PADDLE_TPU_FLASH_BQ", None)
        os.environ.pop("PADDLE_TPU_FLASH_BK", None)
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key, val in dict(config).items():
        if key not in _config or not isinstance(val, dict):
            continue
        _config[key] = {**_config[key], **val}
    kr = _config.get("kernel", {})
    rng = kr.get("tuning_range")
    if kr.get("enable") and rng:
        os.environ["PADDLE_TPU_FLASH_BQ"] = str(int(rng[0]))
        os.environ["PADDLE_TPU_FLASH_BK"] = str(int(rng[-1]))
    else:
        # disabling (or dropping tuning_range) must UNPIN the tile caps —
        # the kernels read env at trace time, so stale values would
        # silently tile every later flash kernel
        os.environ.pop("PADDLE_TPU_FLASH_BQ", None)
        os.environ.pop("PADDLE_TPU_FLASH_BK", None)


def get_config():
    return {k: dict(v) for k, v in _config.items()}
