"""paddle.incubate.autograd — functional transforms (jvp/vjp/Jacobian/
Hessian). Parity: python/paddle/incubate/autograd/__init__.py; the
implementations live in paddle.autograd.functional (jax.jacfwd/jacrev)."""
from ...autograd.functional import jacobian, hessian, jvp, vjp

__all__ = ["jacobian", "hessian", "jvp", "vjp", "Jacobian", "Hessian"]


class Jacobian:
    """Materialized Jacobian with [] indexing (the reference's lazy view —
    computed eagerly here; XLA fuses the full jacrev anyway)."""

    def __init__(self, func, xs, is_batched=False):
        self._jac = jacobian(func, xs, is_batched=is_batched)

    def __getitem__(self, idx):
        return self._jac[idx]

    @property
    def shape(self):
        return self._jac.shape


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        self._hess = hessian(func, xs, is_batched=is_batched)

    def __getitem__(self, idx):
        return self._hess[idx]

    @property
    def shape(self):
        return self._hess.shape
