"""Fused functionals. Parity: python/paddle/incubate/nn/functional/
(fused_multi_head_attention, fused_feedforward, fused_matmul_bias,
fused_rotary_position_embedding, fused_bias_dropout_residual_layer_norm,
fused_multi_transformer) over the CUDA monoliths in
paddle/fluid/operators/fused/*.cu.

TPU-native: each is ONE composite that XLA fuses into a handful of MXU ops —
there is no monolithic kernel to maintain; the attention core routes through
the Pallas flash kernel via F.scaled_dot_product_attention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...tensor.tensor import Tensor, apply_op

__all__ = ["fused_matmul_bias", "fused_linear", "fused_feedforward",
           "fused_multi_head_attention", "fused_rotary_position_embedding",
           "fused_bias_dropout_residual_layer_norm", "fused_linear_activation",
           "fused_multi_transformer"]


def fused_matmul_bias(x, weight, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    def f(a, w, *b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            w = jnp.swapaxes(w, -1, -2)
        out = jnp.matmul(a, w)
        if b:
            out = out + b[0]
        return out
    if bias is not None:
        return apply_op(f, x, weight, bias)
    return apply_op(f, x, weight)


fused_linear = fused_matmul_bias


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    return getattr(F, activation)(out)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      name=None):
    """Parity: fused_feedforward_op.cu — LN→linear→act→dropout→linear→dropout
    →residual(+LN). When PADDLE_TPU_FUSED_FFN=1, the activation is exact
    gelu, the dropouts are inert, and both biases exist, the middle
    linear→gelu→linear runs as the ONE-kernel Pallas fused FFN
    (ops/pallas/fused_ffn.py) — the [*, F] intermediate never touches
    HBM."""
    import os
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], ln1_scale, ln1_bias, ln1_epsilon)
    # inert = the composite's dropouts are identity: zero rates always,
    # or eval mode under upscale_in_train (downscale_in_infer SCALES at
    # inference — not inert)
    drop_inert = (dropout1_rate == 0.0 and dropout2_rate == 0.0) or (
        not training and mode == "upscale_in_train")
    from ...parallel import no_mp_mesh   # mesh query only, no pallas
    if (os.environ.get("PADDLE_TPU_FUSED_FFN") == "1"
            and activation == "gelu" and drop_inert
            and linear1_bias is not None and linear2_bias is not None
            and no_mp_mesh()):   # pallas_call is an SPMD barrier
        from ...ops.pallas.fused_ffn import fused_ffn
        out = apply_op(lambda a, w1, b1, w2, b2: fused_ffn(
            a, w1, b1, w2, b2, "gelu"), x, linear1_weight, linear1_bias,
            linear2_weight, linear2_bias)
    else:
        out = F.linear(x, linear1_weight, linear1_bias)
        out = getattr(F, activation)(out)
        out = F.dropout(out, dropout1_rate, training=training, mode=mode)
        out = F.linear(out, linear2_weight, linear2_bias)
        out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Parity: fused_attention_op.cu (fmha_ref.h). qkv_weight layout
    [3, num_heads, head_dim, embed_dim] as in the reference."""
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    three, n_heads, head_dim, embed = qkv_weight.shape

    def qkv_fn(a, w, *b):
        wr = w.reshape(3 * n_heads * head_dim, embed).T
        out = jnp.matmul(a, wr)
        if b:
            out = out + b[0].reshape(-1)
        return out
    if qkv_bias is not None:
        qkv = apply_op(qkv_fn, x, qkv_weight, qkv_bias)
    else:
        qkv = apply_op(qkv_fn, x, qkv_weight)
    b, s = qkv.shape[0], qkv.shape[1]
    from ...tensor.manipulation import reshape, split as tsplit
    qkv = reshape(qkv, [b, s, 3, n_heads, head_dim])
    q, k, v = tsplit(qkv, 3, axis=2)
    q = reshape(q, [b, s, n_heads, head_dim])
    k = reshape(k, [b, s, n_heads, head_dim])
    v = reshape(v, [b, s, n_heads, head_dim])
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0)
    out = reshape(out, [b, s, n_heads * head_dim])
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style
                                    =True, time_major=False, rotary_emb_base
                                    =10000.0, position_offset=0):
    """Parity: fused_rotary_position_embedding (phi fusion). Layout
    [batch, seq, heads, head_dim]. position_offset (int or traced scalar)
    shifts the rotary positions — the KV-cache decode step at time t rotates
    its single new token with position t, not 0."""
    def rope(x):
        bsz, seq, nh, hd = x.shape
        if sin is None:
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, hd, 2,
                                                        dtype=jnp.float32) / hd))
            t = jnp.arange(seq, dtype=jnp.float32) + position_offset
            freqs = jnp.outer(t, inv)
            s = jnp.sin(freqs)
            c = jnp.cos(freqs)
        else:
            s = sin._data.reshape(seq, hd // 2) if isinstance(sin, Tensor) else sin
            c = cos._data.reshape(seq, hd // 2) if isinstance(cos, Tensor) else cos
        s = s[None, :, None, :]
        c = c[None, :, None, :]

        def f(arr):
            if use_neox_rotary_style:
                x1 = arr[..., : hd // 2]
                x2 = arr[..., hd // 2:]
                ss = jnp.concatenate([s, s], axis=-1)
                cc = jnp.concatenate([c, c], axis=-1)
                rot = jnp.concatenate([-x2, x1], axis=-1)
                return arr * cc.astype(arr.dtype) + rot * ss.astype(arr.dtype)
            x1 = arr[..., 0::2]
            x2 = arr[..., 1::2]
            o1 = x1 * c - x2 * s
            o2 = x2 * c + x1 * s
            return jnp.stack([o1, o2], axis=-1).reshape(arr.shape)
        return f
    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
        else:
            outs.append(apply_op(rope(t), t))
    return tuple(outs)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode="upscale_in_train", name=None):
    """Parity: fused_bias_dropout_residual_layer_norm (phi fusion gpu)."""
    out = x
    if bias is not None:
        out = out + bias
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    out = out + residual
    return F.layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)


def _decode_attn(q, cache, ts, s, attn_mask):
    """Cache attention for the decode step. TPU: the Pallas flash-decode
    kernel over the full static-shape cache with length masking (no
    per-step recompiles); fallback: dense sdpa over the valid prefix."""
    import os
    use_pallas = attn_mask is None and (
        jax.default_backend() == "tpu" or
        os.environ.get("PADDLE_TPU_FORCE_PALLAS") == "1")
    if use_pallas:
        from ...ops.pallas import decode_attention as da
        kc = cache._data[0]          # [B, H, Smax, D]
        if da.is_supported(tuple(q.shape),
                           (kc.shape[0], kc.shape[2], kc.shape[1], kc.shape[3]),
                           q.dtype):
            # inference-only kernel (no VJP) — bypass the autograd tape;
            # the cache is already in kernel layout [B, H, Smax, D], so use
            # the bhsd entry point (no full-cache transposes per step)
            lens = jnp.full((q.shape[0],), ts, jnp.int32)
            out = da.decode_attention_bhsd(
                jnp.swapaxes(jax.lax.stop_gradient(q._data), 1, 2),
                jax.lax.stop_gradient(cache._data[0]),
                jax.lax.stop_gradient(cache._data[1]),
                lens)
            return Tensor(jnp.swapaxes(out, 1, 2))
    k_full = Tensor(jnp.swapaxes(cache._data[0, :, :, :ts + s], 1, 2))
    v_full = Tensor(jnp.swapaxes(cache._data[1, :, :, :ts + s], 1, 2))
    if attn_mask is None and s > 1:
        # match the kernel path: new token r attends the prefix plus new
        # tokens <= r (causal among the chunk)
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(ts + s)[None, :]
        attn_mask = Tensor((cols <= ts + rows)[None, None])
    return F.scaled_dot_product_attention(q, k_full, v_full,
                                          attn_mask=attn_mask)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Parity: fused_multi_transformer_op.cu :: FusedMultiTransformerOp — the
    full decoder stack with KV cache, the north-star inference kernel.
    Returns (out, cache_kvs). Cache layout [2, batch, heads, max_seq, head_dim]
    as in the reference; decode path appends at time_step.
    """
    from ...tensor.manipulation import reshape
    out = x
    new_caches = []
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        residual = out
        if pre_layer_norm:
            h = F.layer_norm(out, out.shape[-1:], ln_scales[i], ln_biases[i],
                             epsilon)
        else:
            h = out
        qkv_w = qkv_weights[i]
        # reference layout (trans_qkvw): [3, heads, head_dim, embed]
        three, nh, hd, emb = qkv_w.shape

        def qkv_fn(a, w, *b):
            wr = w.reshape(3 * nh * hd, emb).T
            o = jnp.matmul(a, wr)
            if b:
                o = o + b[0].reshape(-1)
            return o
        if qkv_biases[i] is not None:
            qkv = apply_op(qkv_fn, h, qkv_w, qkv_biases[i])
        else:
            qkv = apply_op(qkv_fn, h, qkv_w)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = reshape(qkv, [b, s, 3, nh, hd])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        cache = cache_kvs[i] if cache_kvs is not None else None
        ts = None
        if cache is not None and time_step is not None:
            ts = int(time_step.item()) if isinstance(time_step, Tensor) \
                else int(time_step)
        if rotary_embs is not None:
            # decode: the new token sits at absolute position ts, so its
            # rotary phase is ts — not 0
            q, k, _ = fused_rotary_position_embedding(
                q, k, position_offset=ts or 0)
        if ts is not None:

            def upd(c, kk, vv):
                c = c.at[0, :, :, ts:ts + s].set(jnp.swapaxes(kk, 1, 2))
                c = c.at[1, :, :, ts:ts + s].set(jnp.swapaxes(vv, 1, 2))
                return c
            cache._data = upd(cache._data, k._data, v._data)
            attn = _decode_attn(q, cache, ts, s, attn_mask)
            new_caches.append(cache)
        else:
            attn = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                                  is_causal=attn_mask is None)
            if cache_kvs is not None:
                new_caches.append(cache)
        attn = reshape(attn, [b, s, nh * hd])
        attn = F.linear(attn, linear_weights[i], linear_biases[i])
        out = residual + attn
        if not pre_layer_norm:
            out = F.layer_norm(out, out.shape[-1:], ln_scales[i], ln_biases[i],
                               epsilon)
        # FFN
        residual = out
        if pre_layer_norm:
            h = F.layer_norm(out, out.shape[-1:], ffn_ln_scales[i],
                             ffn_ln_biases[i], epsilon)
        else:
            h = out
        h = F.linear(h, ffn1_weights[i], ffn1_biases[i])
        h = getattr(F, activation)(h)
        h = F.linear(h, ffn2_weights[i], ffn2_biases[i])
        out = residual + h
        if not pre_layer_norm:
            out = F.layer_norm(out, out.shape[-1:], ffn_ln_scales[i],
                               ffn_ln_biases[i], epsilon)
    return out, (new_caches if cache_kvs is not None else None)


def softmax_mask_fuse(x, mask, name=None):
    """Fused (x + mask) softmax over the last axis. Parity:
    incubate/nn/functional/fused_softmax_mask.py (CUDA fused kernel) —
    XLA fuses the add into the softmax reduction on TPU, so this wrapper
    IS the fused form."""
    return apply_op(
        lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Fused causal-mask softmax (the reference's GPT-path kernel)."""
    def fn(a):
        s = a.shape[-1]
        mask = jnp.triu(jnp.full((s, s), -1e9, a.dtype), k=1)
        return jax.nn.softmax(a + mask, axis=-1)
    return apply_op(fn, x)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one fused graph (reference
    incubate/nn/functional/fused_dropout_add.py)."""
    dropped = F.dropout(x, p=p, training=training, mode=mode)
    return apply_op(jnp.add, dropped, y)


__all__ += ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
            "fused_dropout_add"]
