from . import nn
from . import autograd
from . import distributed
