from . import nn
from . import asp
from . import autograd
from . import autotune
from . import distributed
from . import optimizer
