"""ASP — automatic structured (n:m) sparsity.

Capability parity: python/paddle/incubate/asp/ (``prune_model``,
``decorate``, ``set_excluded_layers``, ``reset_excluded_layers``,
``calculate_density``, mask-check utilities backed by
``asp/utils.py :: create_mask / check_sparsity``). The reference prunes
FC/conv weights to 2:4 (or n:m) patterns so NVIDIA sparse tensor cores
can consume them.

TPU-native design (NOT a port): the MXU has no sparse mode, so the value
here is (a) API parity for training recipes that prune-then-finetune and
(b) the mask discipline itself — masks are applied as elementwise
multiplies that XLA fuses into the consumer matmul's producer, and the
``decorate``'d optimizer re-applies masks after every step (the
reference's ASPHelper inserts the same masked-update ops). Masks are
plain bf16/f32 0/1 tensors registered as non-trainable state; n:m
selection is magnitude-based along the input dim, vectorized with one
reshape+top-k per weight (no Python loops over rows).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...tensor.tensor import Tensor

__all__ = ["calculate_density", "check_sparsity", "create_mask",
           "decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers"]

_excluded: set = set()          # layer (full) names excluded from pruning
# Masks keyed by the pruned Parameter's uid, with a weakref.finalize
# dropping the entry when the param dies (Tensor has __slots__, so the
# mask can't ride on the object; a name-keyed dict would pin every pruned
# model's masks for the process lifetime). Same pattern as the tensor
# module's persistent-uid registry.
_masks_by_uid: dict = {}


def _set_mask(w, mask):
    import weakref
    if w._uid not in _masks_by_uid:
        weakref.finalize(w, _masks_by_uid.pop, w._uid, None)
    _masks_by_uid[w._uid] = mask


def _get_mask(w):
    return _masks_by_uid.get(w._uid)


def calculate_density(x) -> float:
    """Fraction of nonzeros in ``x`` (reference: asp/utils.py)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return float(jnp.count_nonzero(arr) / arr.size)


def _nm_mask_2d(w: jnp.ndarray, n: int, m: int) -> jnp.ndarray:
    """n:m mask along the INPUT (row) dim of a [in, out] weight: within
    every group of m consecutive input rows per output column, keep the n
    largest |w|. Vectorized: [in, out] -> [in//m, m, out] -> top-n per
    group."""
    rows, cols = w.shape
    pad = (-rows) % m
    wa = jnp.abs(jnp.pad(w, ((0, pad), (0, 0))))
    g = wa.reshape(-1, m, cols)                       # [G, m, out]
    # rank within each group: keep the n largest magnitudes
    order = jnp.argsort(g, axis=1)                    # ascending
    ranks = jnp.argsort(order, axis=1)                # rank of each entry
    keep = ranks >= (m - n)
    mask = keep.reshape(-1, cols)[:rows]
    return mask.astype(w.dtype)


def create_mask(w, func_name: str = "get_mask_2d_best", n: int = 2,
                m: int = 4):
    """n:m sparsity mask for a weight (2-D or conv kernels flattened to
    2-D on the last dim, like the reference's mask helpers)."""
    arr = w._data if isinstance(w, Tensor) else jnp.asarray(np.asarray(w))
    shape = arr.shape
    if arr.ndim < 2:
        return jnp.ones_like(arr)
    w2 = arr.reshape(-1, shape[-1])
    mask = _nm_mask_2d(w2, n, m)
    return mask.reshape(shape)


def check_sparsity(x, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group along the input dim has <= (m - n) zeros'
    complement — i.e. at most n nonzeros (reference: check_mask_2d)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if arr.ndim < 2:
        return True
    w2 = arr.reshape(-1, arr.shape[-1])
    rows, cols = w2.shape
    pad = (-rows) % m
    g = jnp.pad(w2, ((0, pad), (0, 0))).reshape(-1, m, cols)
    nnz = jnp.sum((g != 0).astype(jnp.int32), axis=1)
    return bool(jnp.all(nnz <= n))


def set_excluded_layers(param_names, main_program=None):
    """Exclude layers/params (by name prefix) from pruning."""
    for n in (param_names or []):
        _excluded.add(str(n))


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(model):
    from ...nn.layer.common import Linear
    from ...nn.layer.conv import Conv2D
    for lname, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, (Linear, Conv2D)):
            continue
        if any(lname.startswith(e) or getattr(layer, "full_name", lambda: "")()
               .startswith(e) for e in _excluded):
            continue
        w = getattr(layer, "weight", None)
        if w is not None and w._data is not None and w._data.ndim >= 2:
            yield lname, layer, w


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str =
                "mask_1d", with_mask: bool = True):
    """Prune every Linear/Conv2D weight to n:m sparsity in place.
    with_mask=True registers the masks so a ``decorate``'d optimizer
    keeps them applied during finetuning; with_mask=False is one-shot
    (inference-style) pruning with no retained masks. All mask_algo
    variants select by magnitude along the input dim here (the
    reference's 1d/2d_greedy/2d_best differ in pattern geometry tuned
    for sparse tensor cores, which the MXU does not have).
    Returns {param_name: mask} like the reference."""
    out = {}
    for lname, layer, w in _prunable(model):
        mask = create_mask(w, n=n, m=m)
        w._data = w._data * mask
        if with_mask:
            _set_mask(w, mask)
        out[getattr(w, "name", None) or f"{lname}.weight"] = mask
    return out


class _ASPOptimizer:
    """Wrapper re-applying sparsity masks after every step (the
    reference's ASPHelper-decorated optimizer)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, k):
        return getattr(self._inner, k)

    def step(self):
        self._inner.step()
        params = getattr(self._inner, "_parameter_list", None) or []
        for p in params:
            mask = _get_mask(p)
            if mask is not None and p._data is not None:
                p._data = p._data * mask.astype(p._data.dtype)


def decorate(optimizer):
    """Wrap an optimizer so masked weights stay masked through updates."""
    return _ASPOptimizer(optimizer)
